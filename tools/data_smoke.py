"""End-to-end drill for mx.data — the streaming data plane (CI `data`
job, also driven by tests; ISSUE 17 acceptance).

Four phases, every subprocess wait under a hard timeout (PhaseGuard
discipline — a wedged drill fails, it does not hang the pipeline):

1. **scaling smoke** — ``tools/perf/data_bench.py --quick``: worker
   scaling on the decode-bound pipeline (gate >= 1.5x at 4 workers)
   plus the steady-state ZERO ``data_stall`` / ZERO ``loop_recompile``
   counter-asserts through a real fit.
2. **worker-kill recovery** — a child streams an epoch with
   ``data.worker:sigkill`` armed: every worker's gen-0 corpse is
   respawned over exactly its undelivered range and the delivered
   stream must be IDENTICAL to an unfaulted epoch
   (``data_worker_respawn`` > 0 proves the deaths happened).
3. **zero-cost gate** — a plain 8-device fit fed by ``NDArrayIter``
   must never import ``mxnet_tpu.data`` (lazy module) nor move any
   ``data_*`` counter.
4. **kill -9 / reshard / resume parity** — the PR 10 drill composed
   with the data plane: an 8-device fit streaming through a 2-worker
   ``DataLoader`` is SIGKILLed mid-epoch (no preempt save — resume
   rides the last async batch checkpoint and its loader cursor); the
   second attempt resumes on 4 devices with 4 workers and is killed
   again; the third finishes on 2 devices with 1 worker. Final params
   must be BIT-IDENTICAL to an uninterrupted 8-device baseline, with
   zero steady-state recompiles asserted at every batch of every
   attempt. The model is elastic_smoke's one-hot "lookup regression"
   (each gradient element has exactly one nonzero contributor, so
   parity is immune to FP reduction order across mesh sizes).

Exit 0 + ``DATA-DRILL-OK`` on success; any assertion kills CI.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, OUT = 8, 64, 64, 4
EPOCHS = 3
SEED = 5
PHASE_TIMEOUT = 420.0
# (devices, MXNET_TPU_DATA_WORKERS, fault) per attempt: two mid-epoch
# SIGKILLs, then run to completion — every attempt changes BOTH the
# device world and the worker count
ATTEMPTS = [(8, "2", "fit.batch@5:sigkill"),
            (4, "4", "fit.batch@4:sigkill"),
            (2, "1", None)]


def _dataset(dirpath):
    """One-hot lookup records: record i's payload is e_{i mod FEAT},
    its label a fixed random OUT-vector — the exact-parity dataset of
    tools/elastic_smoke.py, packed as indexed RecordIO."""
    import mxnet_tpu as mx
    rec = os.path.join(dirpath, "onehot.rec")
    idx = os.path.join(dirpath, "onehot.idx")
    if os.path.exists(rec):
        return rec, idx
    x = np.eye(FEAT, dtype=np.float32)[np.arange(NSAMP) % FEAT]
    rng = np.random.RandomState(3)
    y = rng.uniform(-1, 1, (NSAMP, OUT)).astype(np.float32)
    w = mx.recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(NSAMP):
        w.write_idx(i, mx.recordio.pack(
            mx.recordio.IRHeader(OUT, y[i], i, 0), x[i].tobytes()))
    w.close()
    return rec, idx


def _loader(rec, idx):
    import mxnet_tpu as mx
    return mx.data.DataLoader(
        rec, idx_path=idx, batch_size=BATCH,
        transform=mx.data.RawTransform((FEAT,), label_width=OUT),
        shuffle=True, seed=SEED, queue_depth=8, part=(0, 1),
        label_name="label")


def _symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=OUT, no_bias=True,
                               name="lut")
    return mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"),
                                         name="reg")


def _train(data_dir, ckpt_dir=None, out_path=None,
           check_recompiles=False):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    mx.random.seed(SEED)
    ndev = len(jax.devices())
    rec, idx = _dataset(data_dir)
    it = _loader(rec, idx)
    mod = mx.mod.Module(_symbol(), context=[mx.cpu(i) for i in
                                            range(ndev)]
                        if ndev > 1 else mx.cpu(),
                        data_names=("data",), label_names=("label",))
    kw = {}
    if ckpt_dir is not None:
        kw["checkpoint"] = mx.checkpoint.CheckpointConfig(
            ckpt_dir, every_n_batches=2, period_epochs=1, keep_last=0)
        kw["resume_from"] = ckpt_dir if \
            mx.checkpoint.list_checkpoints(ckpt_dir) else None
    if check_recompiles:
        def _no_recompiles(_param):
            n = profiler.get_counter("loop_recompile")
            assert n == 0, "steady-state recompile detected (%d)" % n
        kw["batch_end_callback"] = _no_recompiles
    try:
        mod.fit(it, num_epoch=EPOCHS, eval_metric="mse",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.3,
                                  "momentum": 0.9}, **kw)
    finally:
        it.close()
    arg, _aux = mod.get_params()
    w = {k: v.asnumpy() for k, v in arg.items()}
    if out_path is not None:
        np.savez(out_path, **w)
    return ndev, w


# --------------------------------------------------------- child bodies

def _child_attempt(data_dir, ckpt_dir, out_path):
    from mxnet_tpu import faults, profiler
    spec = os.environ.get("MXNET_TPU_SMOKE_FAULT")
    if spec:
        faults.install(spec)
    ndev, _w = _train(data_dir, ckpt_dir=ckpt_dir, out_path=out_path,
                      check_recompiles=True)
    print("DATA-CHILD-DONE world=%d workers=%s respawns=%d "
          "recompiles=%d stalls=%d"
          % (ndev, os.environ.get("MXNET_TPU_DATA_WORKERS"),
             profiler.get_counter("data_worker_respawn"),
             profiler.get_counter("loop_recompile"),
             profiler.get_counter("data_stall")))
    return 0


def _child_killworkers(data_dir, out_path):
    """Stream one epoch with every worker's gen-0 process SIGKILLed by
    the data.worker fault; write the delivered stream + counters."""
    import mxnet_tpu as mx
    from mxnet_tpu import faults, profiler
    rec, idx = _dataset(data_dir)
    stream = []
    dl = _loader(rec, idx)
    if os.environ.get("MXNET_TPU_SMOKE_FAULT"):
        faults.install(os.environ["MXNET_TPU_SMOKE_FAULT"])
    for batch in dl:
        stream.append(np.argmax(batch.data[0], axis=1).tolist())
    dl.close()
    with open(out_path, "w") as f:
        json.dump({"stream": stream,
                   "respawns": profiler.get_counter(
                       "data_worker_respawn")}, f)
    print("KILLWORKERS-CHILD-DONE")
    return 0


def _child_zero_cost(data_dir):
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    import jax
    mx.random.seed(SEED)
    ndev = len(jax.devices())
    x = np.eye(FEAT, dtype=np.float32)[np.arange(NSAMP) % FEAT]
    y = np.random.RandomState(3).uniform(
        -1, 1, (NSAMP, OUT)).astype(np.float32)
    it = mx.io.NDArrayIter({"data": x}, {"label": y}, batch_size=BATCH)
    mod = mx.mod.Module(_symbol(), context=[mx.cpu(i) for i in
                                            range(ndev)],
                        data_names=("data",), label_names=("label",))
    mod.fit(it, num_epoch=1, eval_metric="mse", optimizer="sgd")
    assert "mxnet_tpu.data" not in sys.modules, \
        "mxnet_tpu.data imported by a fit that never used it"
    bad = {n: profiler.get_counter(n)
           for n in ("data_batches", "data_records", "data_stall",
                     "data_worker_respawn", "data_batch_poisoned")
           if profiler.get_counter(n)}
    assert not bad, "data_* counters moved without the loader: %r" % bad
    print("ZERO-COST-OK")
    return 0


# --------------------------------------------------------------- driver

def _run(argv, env, timeout=PHASE_TIMEOUT, expect_rc=0):
    proc = subprocess.run(argv, env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO)
    dump = "rc=%s\n--- stdout\n%s\n--- stderr\n%s" % (
        proc.returncode, proc.stdout[-4000:], proc.stderr[-4000:])
    assert proc.returncode == expect_rc, dump
    return proc, dump


def main():
    me = os.path.abspath(__file__)
    if "--attempt" in sys.argv:
        i = sys.argv.index("--attempt")
        return _child_attempt(sys.argv[i + 1], sys.argv[i + 2],
                              sys.argv[i + 3])
    if "--baseline" in sys.argv:
        i = sys.argv.index("--baseline")
        _ndev, _w = _train(sys.argv[i + 1], out_path=sys.argv[i + 2])
        print("BASELINE-DONE")
        return 0
    if "--killworkers" in sys.argv:
        i = sys.argv.index("--killworkers")
        return _child_killworkers(sys.argv[i + 1], sys.argv[i + 2])
    if "--zero-cost" in sys.argv:
        return _child_zero_cost(sys.argv[sys.argv.index("--zero-cost")
                                         + 1])

    work = tempfile.mkdtemp(prefix="data_smoke_")
    env_base = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    for k in ("MXNET_TPU_FAULTS", "MXNET_TPU_SMOKE_FAULT",
              "MXNET_TPU_DATA_WORKERS", "MXNET_TPU_DATA_MP",
              "MXNET_TPU_CKPT_TEST_CRASH"):
        env_base.pop(k, None)

    # ---- 1. scaling + steady-state gates (the bench's own GATE) -------
    _p, _d = _run([sys.executable,
                   os.path.join(REPO, "tools", "perf", "data_bench.py"),
                   "--quick"], env_base)
    print("phase 1 ok: scaling + zero-stall/zero-recompile gates")

    # ---- 2. worker-kill recovery: stream identical, respawns > 0 ------
    ref_json = os.path.join(work, "stream-ref.json")
    kill_json = os.path.join(work, "stream-kill.json")
    _run([sys.executable, me, "--killworkers", work, ref_json],
         {**env_base, "MXNET_TPU_DATA_WORKERS": "2"})
    _p, dump = _run([sys.executable, me, "--killworkers", work,
                     kill_json],
                    {**env_base, "MXNET_TPU_DATA_WORKERS": "2",
                     "MXNET_TPU_SMOKE_FAULT": "data.worker@1:sigkill"})
    ref = json.load(open(ref_json))
    killed = json.load(open(kill_json))
    assert killed["respawns"] >= 1, (killed, dump)
    assert killed["stream"] == ref["stream"], \
        "worker-kill replay diverged\n" + dump
    print("phase 2 ok: %d respawns, stream identical"
          % killed["respawns"])

    # ---- 3. zero-cost gate --------------------------------------------
    flags = "--xla_force_host_platform_device_count=8"
    _run([sys.executable, me, "--zero-cost", work],
         {**env_base, "XLA_FLAGS": flags})
    print("phase 3 ok: unused loader never imported, counters silent")

    # ---- 4. kill -9 / reshard / resume parity -------------------------
    base_npz = os.path.join(work, "baseline.npz")
    final_npz = os.path.join(work, "final.npz")
    ckpt_dir = os.path.join(work, "ckpts")
    _run([sys.executable, me, "--baseline", work, base_npz],
         {**env_base, "XLA_FLAGS": flags, "MXNET_TPU_DATA_WORKERS": "2"})
    for att, (ndev, workers, fault) in enumerate(ATTEMPTS):
        env = {**env_base,
               "XLA_FLAGS":
                   "--xla_force_host_platform_device_count=%d" % ndev,
               "MXNET_TPU_DATA_WORKERS": workers}
        if fault:
            env["MXNET_TPU_SMOKE_FAULT"] = fault
        proc, dump = _run(
            [sys.executable, me, "--attempt", work, ckpt_dir,
             final_npz], env,
            expect_rc=-signal.SIGKILL if fault else 0)
        if fault:
            assert "DATA-CHILD-DONE" not in proc.stdout, dump
            print("attempt %d: killed -9 mid-epoch at %d devices / %s "
                  "workers" % (att, ndev, workers))
        else:
            assert "DATA-CHILD-DONE" in proc.stdout, dump
            print("attempt %d: completed at %d devices / %s workers"
                  % (att, ndev, workers))
    ref = np.load(base_npz)
    got = np.load(final_npz)
    assert set(ref.files) == set(got.files)
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)
    print("phase 4 ok: 8->4->2 devices, 2->4->1 workers, kill -9 x2, "
          "params bit-identical to uninterrupted")

    print("DATA-DRILL-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""mx.checkpoint blocking-time bench: how long does the training thread
stop when a checkpoint is taken? (ISSUE 5 — the CheckFreq split.)

A checkpoint is two phases with very different costs: the *snapshot*
(device-side ``jnp.copy`` of params/optimizer-states + queue handoff,
on the training thread) and the *serialization* (device->host fetch,
crc32, npz encode, double fsync — on the background writer). The bench
drives a real ``Module`` mid-training and measures both via the
``ckpt_block_us`` / ``ckpt_write_us`` profiler counters, plus a
synchronous-save baseline where the training thread eats the whole
write.

The acceptance gate (counter-asserted here and in
tests/test_checkpoint_bench.py): async saves block the step loop for
**< 25% of the total serialization time** on the MLP workload.

Usage: python tools/perf/checkpoint_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np

FEAT = 1024
NCLS = 10
BATCH = 32


def _mlp_symbol(hidden):
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=hidden, name="fc2")
    act2 = mx.sym.Activation(fc2, act_type="relu", name="relu2")
    fc3 = mx.sym.FullyConnected(act2, num_hidden=NCLS, name="fc3")
    return mx.sym.SoftmaxOutput(fc3, name="softmax")


def _make_module(hidden):
    import mxnet_tpu as mx
    mx.random.seed(0)
    mod = mx.mod.Module(_mlp_symbol(hidden), context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, FEAT))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(initializer=mx.init.Uniform(0.05))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-3})
    return mod


def _step(mod, rng):
    import mxnet_tpu as mx
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.uniform(-1, 1, (BATCH, FEAT))
                          .astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, NCLS, (BATCH,))
                           .astype(np.float32))])
    mod._fit_step(batch)


def run(quick=False):
    """Returns the record BENCH_checkpoint.json stores. ``quick`` shrinks
    the model and save count for the tier-1 smoke."""
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.checkpoint import CheckpointConfig, CheckpointManager
    import tempfile
    import shutil

    hidden = 256 if quick else 1024
    saves = 4 if quick else 10
    steps_between = 2
    rng = np.random.RandomState(3)
    mod = _make_module(hidden)
    for _ in range(3):                       # warm the fused step
        _step(mod, rng)

    results = {}

    # ---------------------------------------------------- async pipeline
    # The writer is drained between saves (checkpoint periods in real
    # training are minutes, not back-to-back) so ckpt_block_us measures
    # the per-save blocking itself — snapshot copies + queue handoff —
    # not backpressure from an artificially saturated writer. A second
    # pass WITHOUT draining reports the saturated (backpressure) regime.
    base = tempfile.mkdtemp(prefix="ckpt_bench_async_")
    mgr = CheckpointManager(CheckpointConfig(base, async_save=True,
                                             keep_last=2))
    with profiler.counter_delta() as d:
        for _ in range(saves):
            for _ in range(steps_between):
                _step(mod, rng)
            mgr.save_module(mod)
            mgr.wait()
        async_counts = d.all()
    mgr.close()
    block_us = async_counts.get("ckpt_block_us", 0)
    write_us = async_counts.get("ckpt_write_us", 0)
    nbytes = async_counts.get("ckpt_bytes", 0)
    shutil.rmtree(base, ignore_errors=True)

    # ------------------------------------- saturated (backpressure) pass
    base = tempfile.mkdtemp(prefix="ckpt_bench_sat_")
    mgr = CheckpointManager(CheckpointConfig(base, async_save=True,
                                             keep_last=2))
    with profiler.counter_delta() as d:
        for _ in range(saves):
            _step(mod, rng)
            mgr.save_module(mod)
        mgr.wait()
        sat_counts = d.all()
    mgr.close()
    shutil.rmtree(base, ignore_errors=True)

    # ------------------------------------------------- synchronous saves
    base = tempfile.mkdtemp(prefix="ckpt_bench_sync_")
    mgr = CheckpointManager(CheckpointConfig(base, async_save=False,
                                             keep_last=2))
    with profiler.counter_delta() as d:
        for _ in range(saves):
            for _ in range(steps_between):
                _step(mod, rng)
            mgr.save_module(mod)
        sync_counts = d.all()
    mgr.close()
    sync_block_us = sync_counts.get("ckpt_block_us", 0)
    shutil.rmtree(base, ignore_errors=True)

    results = {
        "saves": saves,
        "ckpt_mbytes": round(nbytes / saves / 1e6, 3),
        "async_block_ms_per_save": round(block_us / saves / 1e3, 3),
        "async_write_ms_per_save": round(write_us / saves / 1e3, 3),
        "block_fraction_of_write": round(block_us / max(1, write_us), 4),
        "saturated_block_ms_per_save": round(
            sat_counts.get("ckpt_block_us", 0) / saves / 1e3, 3),
        "saturated_backpressure_waits": sat_counts.get(
            "ckpt_backpressure_wait", 0),
        "sync_block_ms_per_save": round(sync_block_us / saves / 1e3, 3),
        "async_vs_sync_block_speedup": round(
            sync_block_us / max(1, block_us), 2),
        "saved": async_counts.get("ckpt_saved", 0),
        "write_failed": async_counts.get("ckpt_write_failed", 0)
        + sat_counts.get("ckpt_write_failed", 0)
        + sync_counts.get("ckpt_write_failed", 0),
    }
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    t0 = time.perf_counter()
    results = run(quick=args.quick)
    record = {
        "bench": "checkpoint",
        "quick": bool(args.quick),
        "elapsed_s": round(time.perf_counter() - t0, 1),
        "results": results,
    }
    print(json.dumps(record, indent=2))
    frac = results["block_fraction_of_write"]
    if not args.quick:
        assert frac < 0.25, \
            "async save blocked %.1f%% of write time (gate: <25%%)" \
            % (100 * frac)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Measure achievable HBM bandwidth (read+write) on this chip."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
from jax import lax

ITERS = 300
for mb in (64, 256, 512):
    n = mb * 1024 * 1024 // 2  # bf16 elements
    x = jnp.ones((n,), jnp.bfloat16)

    def body(c, _):
        return c + jnp.bfloat16(1), ()

    @jax.jit
    def run(x):
        out, _ = lax.scan(body, x, None, length=ITERS)
        return out[0].astype(jnp.float32)

    r = run(x); r.block_until_ready(); float(r)
    t0 = time.perf_counter(); float(run(x))
    dt = (time.perf_counter() - t0) / ITERS
    bw = 2 * mb / 1024 / dt  # read + write, GiB/s
    print("array %4d MiB: %.2f ms/pass  %.0f GiB/s (r+w)" % (mb, dt * 1e3, bw))

"""Grouped-optimizer-update sweep (the PR 9 close-out lever, landed):
bind + first-step wall of a deep scanned transformer with
``MXNET_TPU_GROUP_UPDATE`` on vs off, at L=32 and L=96.

Scan-over-layers already lowers the FORWARD through one ``lax.scan``,
but the fused step still traced L per-layer optimizer-update copies —
the residual O(L) program eqns PR 9's close-out note flagged. Grouping
updates each per-layer parameter family as ONE vmapped body over the
stacked ``(L, ...)`` arrays, so the update traces once per family.

Each arm runs in a fresh subprocess (clean jax caches); results merge
into ``BENCH_compile_time.json`` under ``"grouped_update"`` next to the
PR 9 scan sweep. Also records the fused-step jaxpr equation counts both
ways — the deterministic, box-speed-independent form of the claim.

Usage: python tools/perf/group_update_sweep.py [--layers 32,96] [--out
BENCH_compile_time.json]
"""
import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_CHILD = r"""
import json, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.models import transformer

L = int(sys.argv[1])
group = sys.argv[2] == "1"
mx.config.set("MXNET_TPU_GROUP_UPDATE", group)
mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")

D, H, T, V, B = 128, 4, 64, 256, 4
sym = transformer.get_symbol(vocab_size=V, num_layers=L, d_model=D,
                             n_heads=H, seq_len=T)
rng = np.random.RandomState(0)
x = rng.randint(0, V, (B, T)).astype(np.float32)
y = rng.randint(0, V, (B, T)).astype(np.float32)

import jax
jax.jit(lambda v: v * 2)(np.ones(4))    # warm jax itself

t0 = time.perf_counter()
mod = mx.mod.Module(sym, context=mx.cpu(0))
mod.bind(data_shapes=[("data", (B, T))],
         label_shapes=[("softmax_label", (B, T))])
mod.init_params(mx.init.Xavier())
mod.init_optimizer(optimizer="adam",
                   optimizer_params={"learning_rate": 0.01})
bind_secs = time.perf_counter() - t0

# deterministic form: count fused-step jaxpr equations both ways
params = {n: mod._exec.arg_dict[n].data
          for n in mod._param_names}
states = mod._fused_states
aux = {n: a.data for n, a in mod._exec.aux_dict.items()}
inputs = {n: mod._exec.arg_dict[n].data
          for n in ("data", "softmax_label")}
import jax.numpy as jnp
jaxpr = jax.make_jaxpr(
    lambda *a: mod._fused_jit.__wrapped__(*a))(
    params, states, aux, inputs, {}, jax.random.PRNGKey(0),
    jnp.float32(0.01), jnp.int32(1))
n_eqns = len(jaxpr.jaxpr.eqns)

t0 = time.perf_counter()
db = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
mod._fit_step(db)
jax.block_until_ready(mod._exec.arg_dict["lm_head_weight"].data)
first_step_secs = time.perf_counter() - t0

print(json.dumps({
    "layers": L, "grouped": group,
    "bind_secs": round(bind_secs, 3),
    "first_step_secs": round(first_step_secs, 3),
    "fused_step_eqns": n_eqns,
    "update_groups": mx.profiler.gauges().get("fused_update_groups"),
}))
"""


def _arm(layers, group):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=ROOT)
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, str(layers), "1" if group else "0"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise SystemExit("arm L=%d group=%s failed:\n%s\n%s"
                         % (layers, group, proc.stdout[-2000:],
                            proc.stderr[-3000:]))
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("arm produced no JSON")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", default="32,96")
    ap.add_argument("--out", default=os.path.join(ROOT,
                                                  "BENCH_compile_time.json"))
    args = ap.parse_args()

    configs = []
    for L in (int(s) for s in args.layers.split(",")):
        on = _arm(L, True)
        off = _arm(L, False)
        rec = {
            "layers": L,
            "bind_plus_first_step_grouped":
                round(on["bind_secs"] + on["first_step_secs"], 2),
            "bind_plus_first_step_per_param":
                round(off["bind_secs"] + off["first_step_secs"], 2),
            "speedup": round(
                (off["bind_secs"] + off["first_step_secs"])
                / max(1e-9, on["bind_secs"] + on["first_step_secs"]), 2),
            "fused_step_eqns_grouped": on["fused_step_eqns"],
            "fused_step_eqns_per_param": off["fused_step_eqns"],
            "eqn_ratio": round(off["fused_step_eqns"]
                               / max(1, on["fused_step_eqns"]), 2),
            "update_groups": on["update_groups"],
        }
        configs.append(rec)
        print(json.dumps(rec), flush=True)

    try:
        with open(args.out) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"metric": "compile_time_levers", "configs": []}
    doc["grouped_update"] = {
        "note": "MXNET_TPU_GROUP_UPDATE on-vs-off under scan-over-layers "
                "(cpu-host; adam, d_model=128, seq=64): the fused step's "
                "per-layer optimizer-update eqns collapse to one vmapped "
                "body per parameter family",
        "configs": configs,
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, args.out)
    print("updated %s" % args.out)


if __name__ == "__main__":
    main()

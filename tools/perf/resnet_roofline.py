"""Per-layer roofline for ResNet-50 v2 training, batch 256, bf16.

For every conv: t_lower_bound = max(flops / PEAK_FLOPS, bytes / HBM_BW).
Train counts 3x forward flops and ~3x forward bytes (fwd, dx, dw each
stream the activation-sized arrays once). Elementwise columns add the
BN/ReLU/residual traffic at 2 bytes/elem/pass, assuming perfect fusion
into one read+write per tensor per pass.

No chip needed -- pure arithmetic; constants from tools/perf/hbm_bw.py
(measured ~500-540 GB/s achievable) and the 197 TF/s bf16 peak.
"""
B = 256
PEAK = 197e12
BWS = [537e9, 819e9]   # measured-achievable and nominal

# (name, H_in, Cin, Cout, k, stride, count)
LAYERS = [
    ("stem 7x7/2",      224, 3,    64,   7, 2, 1),
    ("s1 c1 64->64",     56, 64,   64,   1, 1, 1),
    ("s1 c2 3x3",        56, 64,   64,   3, 1, 3),
    ("s1 c3 64->256",    56, 64,   256,  1, 1, 3),
    ("s1 sc 64->256",    56, 64,   256,  1, 1, 1),
    ("s1 c1 256->64",    56, 256,  64,   1, 1, 2),
    ("s2 c1 256->128",   56, 256,  128,  1, 1, 1),
    ("s2 c2 3x3/2",      56, 128,  128,  3, 2, 1),
    ("s2 c2 3x3",        28, 128,  128,  3, 1, 3),
    ("s2 c3 128->512",   28, 128,  512,  1, 1, 4),
    ("s2 sc 256->512/2", 56, 256,  512,  1, 2, 1),
    ("s2 c1 512->128",   28, 512,  128,  1, 1, 3),
    ("s3 c1 512->256",   28, 512,  256,  1, 1, 1),
    ("s3 c2 3x3/2",      28, 256,  256,  3, 2, 1),
    ("s3 c2 3x3",        14, 256,  256,  3, 1, 5),
    ("s3 c3 256->1024",  14, 256,  1024, 1, 1, 6),
    ("s3 sc 512->1024/2",28, 512,  1024, 1, 2, 1),
    ("s3 c1 1024->256",  14, 1024, 256,  1, 1, 5),
    ("s4 c1 1024->512",  14, 1024, 512,  1, 1, 1),
    ("s4 c2 3x3/2",      14, 512,  512,  3, 2, 1),
    ("s4 c2 3x3",         7, 512,  512,  3, 1, 2),
    ("s4 c3 512->2048",   7, 512,  2048, 1, 1, 3),
    ("s4 sc 1024->2048/2",14,1024, 2048, 1, 2, 1),
    ("s4 c1 2048->512",   7, 2048, 512,  1, 1, 2),
    ("fc 2048->1000",     1, 2048, 1000, 1, 1, 1),
]

def main():
    tot_f = 0.0
    tot_t = {bw: [0.0, 0.0] for bw in BWS}  # conv-only, conv+elemwise
    print("%-20s %9s %9s  %s" % ("layer", "GF(train)", "int(F/B)",
                                 "  ".join("t@%dGB/s(ms)" % (b/1e9)
                                           for b in BWS)))
    for name, H, ci, co, k, s, cnt in LAYERS:
        Ho = H // s
        F = 2.0 * B * Ho * Ho * co * ci * k * k * cnt      # fwd flops
        bytes_f = 2.0 * cnt * (B * H * H * ci + B * Ho * Ho * co
                               + co * ci * k * k)
        Ftr, Btr = 3 * F, 3 * bytes_f
        # elementwise: BN (read y, write y) + ReLU fused + residual adds:
        # ~2 extra passes over y fwd, ~4 bwd (dy reads, BN stats)
        Bel = Btr + 6 * 2.0 * cnt * B * Ho * Ho * co
        line = "%-20s %9.1f %9.1f" % (name, Ftr / 1e9, Ftr / Btr)
        for bw in BWS:
            t1 = max(Ftr / PEAK, Btr / bw)
            t2 = max(Ftr / PEAK, Bel / bw)
            tot_t[bw][0] += t1
            tot_t[bw][1] += t2
            line += "  %6.2f/%6.2f" % (t1 * 1e3, t2 * 1e3)
        tot_f += Ftr
        print(line)
    print()
    print("total train GFLOPs: %.0f  (%.1f GF/img fwd)"
          % (tot_f / 1e9, tot_f / 3 / B / 1e9))
    for bw in BWS:
        for j, tag in enumerate(("conv-only", "conv+elemwise")):
            t = tot_t[bw][j]
            print("roofline @%3d GB/s %-14s: %6.1f ms/step  %6.0f img/s  "
                  "MFU ceiling %4.1f%%"
                  % (bw / 1e9, tag, t * 1e3, B / t,
                     100 * tot_f / PEAK / t))
    meas_ms = 110.8  # BENCH_r04: 2310 img/s
    print("measured (BENCH_r04): 110.8 ms/step, 2310 img/s, 28.8%% MFU")

if __name__ == "__main__":
    main()

"""GPipe vs 1F1B: compiled activation memory and step time.

Runs the same pipelined transformer (PipelineModule, 4 body stages on a
virtual 4-device CPU mesh) under both schedules and reports XLA's
compiled memory analysis — 1F1B's point is O(n_stages) in-flight
activations vs GPipe's O(M).

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
  python tools/perf/pipeline_schedule_compare.py
"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.models import transformer

B, T, M = 32, 64, 16
D = 128

def build(schedule):
    stages = transformer.get_pipeline_stages(
        vocab_size=64, n_stages=4, layers_per_stage=1, d_model=D,
        n_heads=4, seq_len=T)
    mod = mx.mod.PipelineModule(stages, n_microbatches=M,
                                schedule=schedule)
    mod.bind(data_shapes=[("data", (B, T))],
             label_shapes=[("softmax_label", (B, T))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer("sgd", {"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    db = mx.io.DataBatch(
        data=[mx.nd.array(rng.randint(0, 64, (B, T)).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 64, (B, T)).astype(np.float32))])
    return mod, db

for schedule in ("gpipe", "1f1b"):
    mod, db = build(schedule)
    mod.fit_step(db)  # compile
    # memory analysis of the traced+compiled step
    lowered = None
    try:
        import jax.numpy as jnp
        args = [mod._dev_params]
        if schedule == "1f1b":
            args.append(mod._dev_aux)
        args += [mod._dev_states]
        x = np.asarray(db.data[0].asnumpy())
        inputs = {"data": jnp.asarray(
            x.reshape((M, B // M) + x.shape[1:]))}
        y = np.asarray(db.label[0].asnumpy())
        inputs["softmax_label"] = jnp.asarray(
            y.reshape((M, B // M) + y.shape[1:]))
        args += [inputs, jax.random.PRNGKey(0),
                 jnp.asarray(0.1, jnp.float32), jnp.asarray(1, jnp.int32)]
        comp = mod._step_jit.lower(*args).compile()
        ma = comp.memory_analysis()
        temp = getattr(ma, "temp_size_in_bytes", None)
        print("%s: temp %.1f MB  (args %.1f MB, out %.1f MB)"
              % (schedule, (temp or 0) / 1e6,
                 getattr(ma, "argument_size_in_bytes", 0) / 1e6,
                 getattr(ma, "output_size_in_bytes", 0) / 1e6))
    except Exception as e:
        print(schedule, "memory_analysis unavailable:", e)
    t0 = time.perf_counter()
    for _ in range(5):
        mod.fit_step(db)
    np.asarray(mod.get_params()[0][list(mod.get_params()[0])[0]])
    print("%s: %.1f ms/step" % (schedule,
                                (time.perf_counter() - t0) / 5 * 1e3))

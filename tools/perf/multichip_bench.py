"""Multi-chip training bench: REAL ``fit`` runs per mesh shape
(ISSUE 14 — the MULTICHIP dryruns promoted to benched end-to-end runs).

For each mesh shape of the unified ``data x fsdp x tp`` SpecLayout
(pure-dp, dp x fsdp, dp x tp, dp x fsdp x tp — the 8-device virtual
mesh, or real TPU shapes when hardware is reachable) this driver runs a
real ``Module.fit`` and records:

* **steps/s + MFU** from the always-on mx.obs accounting (MFU per mesh
  shape — the obs record now carries the mesh; peak FLOP/s comes from
  the TPU device-kind table, or a calibrated host-matmul peak on CPU so
  the number is meaningful rather than fabricated);
* **per-axis collective bytes** of the actual fused-step executable
  (the PR 8 analyzer's collective walk over the post-GSPMD HLO),
  cross-checked against the analytic comm model where one is exact:
  - pure dp: the gradient all-reduce over ``data`` moves exactly the
    grad-bearing parameter bytes;
  - dp x tp: the same reduction shrinks to ``bytes/tp_shards`` per
    tensor-parallel parameter (each device reduces only its shard);
  both must agree within +-25% (BENCH gate). The fsdp arms record the
  full per-axis table too; at bench batch sizes GSPMD legitimately
  prefers resharding the (small) activations over gathering the (large)
  weights, so the fsdp-axis gate is the RESIDENT-bytes claim below, not
  a gather-bytes prediction.
* **per-device resident param+state bytes**, proving the FSDP axis
  recovers what the analyzer's ``fsdp-opportunity`` audit promised:
  dp x fsdp residency ~= replicated/fsdp (within padding + the
  min-shard-bytes threshold), with the audited recovered-bytes number
  validated against the measured drop.

Output: one JSON line per shape as it completes (wedge-proof, the
bench.py protocol), then the merged record — written to
``BENCH_multichip.json`` when ``--out`` is given.

``--smoke`` is the CI ``multichip`` job: dp x fsdp only, hard deadline,
asserts nonzero steps/s, ``check_islands`` zero findings, the comm
cross-check, the residency ratio, and the zero-cost gate (a plain fit
in a subprocess never imports ``parallel.layout`` and moves no new
counters).
"""
import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, ROOT)

# the virtual-mesh rig: 8 CPU devices unless real accelerators exist
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

B, DIN, HIDDEN, D2, NCLASS = 64, 1024, 2048, 1024, 16
NSAMP, EPOCHS = 512, 3
COMM_TOL = 0.25


def _build_symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=HIDDEN, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=D2, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=NCLASS, name="head")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _calibrated_peak():
    """Per-device peak FLOP/s: the TPU device-kind table when known,
    else a measured host matmul rate — a real denominator, so the CPU
    fallback MFU is 'fraction of this host's matmul peak', not a
    fabricated number. The 8 virtual CPU devices all share ONE host's
    cores, and the MFU gauge multiplies the per-device peak by the
    device count — so the host rate is split across the virtual devices
    to keep that product the true host peak."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.obs import mfu as _mfu
    peak = _mfu.peak_flops(jax.devices()[0].device_kind)
    if peak:
        return peak, "device-kind table"
    n = 1024
    a = jnp.asarray(np.random.RandomState(0).rand(n, n), jnp.float32)
    f = jax.jit(lambda x: x @ x)
    f(a).block_until_ready()
    t0 = time.perf_counter()
    iters = 8
    for _ in range(iters):
        a = f(a)
    a.block_until_ready()
    dt = time.perf_counter() - t0
    host = 2 * n ** 3 * iters / dt
    return host / len(jax.devices()), "calibrated-host-matmul/n_dev"


def _resident_bytes(mod):
    """Per-device resident bytes of parameters + optimizer states (what
    FSDP is supposed to shrink): sum of ONE device's shard of every
    array."""
    import jax
    import numpy as np

    def shard_bytes(arr):
        shp = arr.sharding.shard_shape(arr.shape)
        return int(np.prod(shp, dtype=np.int64)) * arr.dtype.itemsize

    params = 0
    for n in mod._param_names:
        params += shard_bytes(mod._exec.arg_dict[n].data)
    states = 0
    for leaf in jax.tree_util.tree_leaves(mod._fused_states or {}):
        states += shard_bytes(leaf)
    return params, states


def _fused_call_args(mod):
    """Reconstruct the fused step's call signature (exactly what run()
    passes) so the executable can be lowered for the collective walk."""
    import jax
    import jax.numpy as jnp
    ex = mod._exec
    pnames = [n for n in mod._param_names
              if mod._grad_req.get(n, "null") != "null"]
    params = {n: ex.arg_dict[n].data for n in pnames}
    # inputs must be batch-sharded exactly as the fit loop places them
    # (fit's epoch-end set_params re-placed the input buffers replicated
    # — lowering with THOSE would partition a collective-free program)
    inputs = {}
    for n in (set(mod._data_names) | set(mod._label_names)
              | set(mod._state_names)):
        if n not in ex.arg_dict:
            continue
        val = ex.arg_dict[n].data
        if mod._batch_sharding is not None:
            import jax as _jax
            val = _jax.device_put(val, mod._batch_sharding)
        inputs[n] = val
    frozen = {n: ex.arg_dict[n].data for n in mod._param_names
              if n not in pnames}
    aux = {n: a.data for n, a in ex.aux_dict.items()}
    key = jax.random.fold_in(ex._base_key, 1)
    return (params, mod._fused_states, aux, inputs, frozen, key,
            jnp.asarray(0.1, jnp.float32), jnp.asarray(1, jnp.int32))


def _collective_walk(mod):
    """Per-axis collective buffer/link bytes of the REAL fused-step
    program (the analyzer's PR 8 machinery over the lowered HLO)."""
    from mxnet_tpu.analysis.sharding_passes import collectives_from_hlo
    txt = mod._fused_jit.lower(*_fused_call_args(mod)).compile().as_text()
    per_axis = {}
    for rec in collectives_from_hlo(txt, mesh=mod._mesh):
        k = "x".join(rec["axes"]) or "<unattributed>"
        agg = per_axis.setdefault(k, {"bytes": 0, "link_bytes": 0,
                                      "count": 0})
        agg["bytes"] += rec["bytes"]
        agg["link_bytes"] += rec["link_bytes"]
        agg["count"] += 1
    return per_axis


def _comm_model(mod, layout):
    """The analytic side of the cross-check: per-axis expectations that
    are EXACT by construction (gradient reductions), keyed by the axis
    group GSPMD emits them under. Activation collectives and GSPMD's
    cost-based resharding choices are deliberately not modeled — the
    gate covers only the modeled axes."""
    from mxnet_tpu.analysis.sharding_passes import _spec_axes
    if layout.fsdp > 1:
        # fsdp arms: GSPMD picks between weight-gather and
        # activation-reshard strategies (and reduce-scatter vs
        # all-reduce, merged axis groups) on cost — no closed-form
        # per-axis byte prediction holds across batch sizes. Their
        # gated claim is the resident-bytes one; the full measured
        # per-axis table is still recorded.
        return {}
    fsdp_ax = layout.fsdp_axis
    sizes = {str(a): int(s) for a, s in
             zip(mod._mesh.axis_names, mod._mesh.devices.shape)}
    dp_axes = [ax for ax in (layout.data_axis, fsdp_ax)
               if sizes.get(ax, 1) > 1]
    model = {}
    for n in mod._param_names:
        if mod._grad_req.get(n, "null") == "null":
            continue
        arr = mod._exec.arg_dict[n].data
        spec_axes = set(_spec_axes(arr.sharding.spec))
        shards = 1
        for ax in spec_axes:
            shards *= sizes.get(ax, 1)
        # this param's gradient reduces over the dp axes it is NOT
        # already sharded over; the reduce moves its SHARD bytes
        reduce_axes = tuple(ax for ax in dp_axes if ax not in spec_axes)
        if not reduce_axes:
            continue
        key = "x".join(reduce_axes)
        model[key] = model.get(key, 0) + arr.nbytes // shards
    return model


def run_shape(tag, layout, peak, peak_source, audit_recovered=None):
    import numpy as np
    import mxnet_tpu as mx

    rec = {"shape": tag, "mesh": layout.axes(), "batch": B,
           "peak_flops_per_device": peak, "peak_source": peak_source}
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (NSAMP, DIN)).astype(np.float32)
    Y = rng.randint(0, NCLASS, (NSAMP,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=B)

    mx.random.seed(13)
    mx.config.set("MXNET_TPU_OBS_PEAK_FLOPS",
                  peak if peak_source != "device-kind table" else 0.0)
    t0 = time.perf_counter()
    # the single context is a placeholder — with a layout bound, bind
    # builds the mesh over ALL default-backend devices (TPU when
    # attached, the 8-device virtual CPU mesh otherwise)
    mod = mx.mod.Module(_build_symbol(), context=mx.cpu(), layout=layout)
    rc0 = mx.profiler.counters().get("loop_recompile", 0)
    mod.fit(it, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Uniform(0.05), eval_metric="acc")
    rec["fit_wall_secs"] = round(time.perf_counter() - t0, 2)
    rec["loop_recompile"] = \
        mx.profiler.counters().get("loop_recompile", 0) - rc0

    # obs: steps/s + MFU per mesh shape (one collect closes the window
    # that opened at the warmup step)
    rep = mx.obs.report()
    ours = [e for e in rep["executors"] if e.get("mesh")]
    if ours:
        e = max(ours, key=lambda r: r.get("steps_per_sec") or 0)
        rec["steps_per_sec"] = round(e["steps_per_sec"], 3) \
            if e.get("steps_per_sec") else None
        rec["mfu"] = round(e["mfu"], 5) if e.get("mfu") is not None \
            else None
        rec["flops_per_step"] = e.get("flops_per_step")

    # the real executable's collectives vs the analytic model
    measured = _collective_walk(mod)
    model = _comm_model(mod, layout)
    rec["comm_per_axis_bytes"] = {k: v["bytes"]
                                  for k, v in sorted(measured.items())}
    rec["comm_per_axis_link_bytes"] = {
        k: v["link_bytes"] for k, v in sorted(measured.items())}
    rec["comm_model_bytes"] = model
    checks = {}
    for axis, want in model.items():
        got = measured.get(axis, {}).get("bytes", 0)
        checks[axis] = {"measured": got, "model": want,
                        "ratio": round(got / want, 3) if want else None,
                        "ok": bool(want and
                                   abs(got - want) <= COMM_TOL * want)}
    rec["comm_check"] = checks

    res_p, res_s = _resident_bytes(mod)
    rec["resident_param_bytes_per_device"] = res_p
    rec["resident_state_bytes_per_device"] = res_s
    rec["resident_param_state_bytes_per_device"] = res_p + res_s
    if audit_recovered is not None:
        rec["audit_recovered_bytes_per_device_full_fsdp"] = audit_recovered
    mx.config.reset("MXNET_TPU_OBS_PEAK_FLOPS")
    return rec, mod


def _audit_fsdp_opportunity(mod):
    """The analyzer's fsdp-opportunity numbers for a pure-dp module —
    the promise the dp x fsdp arm must cash."""
    report = mod.analyze(sharding=True, collectives=False)
    total = 0
    for f in report.findings:
        if f.code == "fsdp-opportunity":
            total += int(f.detail.get("recovered_bytes_per_device", 0))
    return total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write the merged record here "
                         "(e.g. BENCH_multichip.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: dp x fsdp only + assertions + "
                         "zero-cost subprocess")
    args = ap.parse_args()

    import jax
    n_dev = len(jax.devices())
    if n_dev < 8:
        print(json.dumps({"skipped": "need 8 devices, have %d" % n_dev}))
        return 0
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SpecLayout

    peak, peak_source = _calibrated_peak()

    shapes = [("dp%d" % n_dev, SpecLayout(data=n_dev)),
              ("dp2xfsdp%d" % (n_dev // 2), SpecLayout(data=2,
                                                       fsdp=n_dev // 2)),
              ("dp2xtp%d" % (n_dev // 2), SpecLayout(data=2,
                                                     tp=n_dev // 2)),
              ("dp2xfsdp2xtp2", SpecLayout(data=2, fsdp=2, tp=2))]
    if args.smoke:
        shapes = [shapes[0], shapes[1]]

    records = {}
    audit_recovered = None
    dp_resident = None
    deadline = time.monotonic() + float(os.environ.get(
        "MULTICHIP_BENCH_TIMEOUT", "900"))
    for tag, layout in shapes:
        if time.monotonic() > deadline:
            records[tag] = {"shape": tag, "error": "bench deadline"}
            print(json.dumps(records[tag]), flush=True)
            continue
        rec, mod = run_shape(tag, layout, peak, peak_source,
                             audit_recovered=audit_recovered
                             if layout.fsdp > 1 else None)
        if layout.fsdp == 1 and layout.tp == 1:
            # the pure-dp module is what the fsdp-opportunity audit
            # speaks about; its promise gates the fsdp arm below
            audit_recovered = _audit_fsdp_opportunity(mod)
            dp_resident = rec["resident_param_bytes_per_device"]
            rec["audit_fsdp_opportunity_bytes_per_device"] = \
                audit_recovered
        if layout.fsdp > 1 and layout.tp == 1 and dp_resident:
            # param-only comparison: the audit speaks about parameters
            # (states recover the same fraction again — recorded above);
            # tp arms recover via a different mechanism and are excluded
            measured_rec = dp_resident - \
                rec["resident_param_bytes_per_device"]
            # the audit promises (n_dev-1)/n_dev recovery at FULL fsdp;
            # scale to THIS layout's (fsdp-1)/fsdp before comparing
            scaled = None
            if audit_recovered:
                scaled = int(audit_recovered
                             * ((layout.fsdp - 1) / layout.fsdp)
                             / ((n_dev - 1) / n_dev))
            rec["fsdp_recovered_bytes_per_device"] = measured_rec
            rec["fsdp_recovered_vs_audit"] = {
                "measured": measured_rec, "audit_scaled": scaled,
                "ratio": round(measured_rec / scaled, 3) if scaled
                else None}
        records[tag] = rec
        print(json.dumps(rec), flush=True)

    merged = {
        "metric": "multichip_fit",
        "n_devices": n_dev,
        "platform": jax.devices()[0].device_kind,
        "model": "mlp %d-%d-%d-%d, batch %d, sgd+momentum, %d epochs x "
                 "%d batches" % (DIN, HIDDEN, D2, NCLASS, B, EPOCHS,
                                 NSAMP // B),
        "peak_flops_per_device": peak,
        "peak_source": peak_source,
        "comm_tolerance": COMM_TOL,
        "shapes": records,
    }
    print(json.dumps(merged), flush=True)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1)
            f.write("\n")
        os.replace(tmp, args.out)

    if args.smoke:
        return _smoke_asserts(records, n_dev)
    return 0


def _smoke_asserts(records, n_dev):
    import mxnet_tpu as mx
    dp = records["dp%d" % n_dev]
    fsdp = records["dp2xfsdp%d" % (n_dev // 2)]
    # 1. real benched fit: nonzero steps/s, zero steady-state recompiles
    for rec in (dp, fsdp):
        assert rec.get("steps_per_sec"), \
            "no steps/s for %s: %s" % (rec.get("shape"), rec)
        assert rec["loop_recompile"] == 0, rec
    # 2. comm cross-check on every modeled axis
    for rec in (dp, fsdp):
        for axis, chk in rec["comm_check"].items():
            assert chk["ok"], "comm model mismatch on %s/%s: %s" \
                % (rec["shape"], axis, chk)
    assert dp["comm_check"], "pure-dp must model its data-axis reduce"
    # 3. FSDP residency: ~1/fsdp of replicated for the sharded bytes
    rva = fsdp["fsdp_recovered_vs_audit"]
    assert rva["audit_scaled"] and rva["ratio"] is not None, rva
    assert abs(rva["ratio"] - 1.0) <= 0.25, \
        "fsdp recovered bytes diverge from the audit promise: %s" % rva
    # 4. islands: zero cross-island disagreements on the canonical mesh
    from mxnet_tpu.analysis import check_islands
    from mxnet_tpu.parallel import SpecLayout, sharding_islands
    rep = check_islands(sharding_islands(),
                        mesh=SpecLayout(data=2, fsdp=2, tp=2).mesh())
    assert len(rep.findings) == 0, \
        "island disagreement: %s" % [f.format() for f in rep.findings]
    # 5. zero-cost gate: a PLAIN fit (no layout) in a fresh process
    # never imports parallel.layout and moves no layout/group counters
    code = r"""
import sys
import numpy as np
import mxnet_tpu as mx
rng = np.random.RandomState(0)
it = mx.io.NDArrayIter(rng.uniform(-1, 1, (32, 16)).astype(np.float32),
                       rng.randint(0, 4, (32,)).astype(np.float32),
                       batch_size=8)
net = mx.sym.SoftmaxOutput(
    mx.sym.FullyConnected(mx.sym.Variable('data'), num_hidden=4),
    name='softmax')
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=1, optimizer='sgd',
        initializer=mx.init.Uniform(0.05))
assert 'mxnet_tpu.parallel.layout' not in sys.modules, \
    'layout imported in a plain fit'
c = mx.profiler.counters()
assert not c.get('fused_update_grouped'), c
print('ZERO-COST-OK')
"""
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0 and "ZERO-COST-OK" in proc.stdout, \
        proc.stdout + proc.stderr
    print("MULTICHIP-SMOKE-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""End-to-end ``Module.fit`` throughput: the synchronous per-batch loop vs
the async pipeline (ISSUE 4 — bounded in-flight dispatch + device-resident
metrics + device prefetch).

Two workloads:

* **mlp (input-bound)** — an MLP fed by an iterator modeling a record
  pipeline: a fixed storage/decode latency per batch (the networked-
  storage regime; the stall releases the GIL exactly like a disk read)
  plus a numpy normalize pass. The synchronous loop serializes input
  latency, H2D placement, the step, and the per-batch metric ``asnumpy``
  round-trip; the async loop overlaps all four, so steps/s is gated by
  max(input, step) instead of their sum. This is the config the
  acceptance bar applies to (>= 1.5x, best-of-3).
* **resnet_stem (compute-bound)** — conv/BN/pool/FC on 3x32x32 inputs
  with a cheap in-memory iterator: the step dominates, async ~ sync
  (reported as a no-regression reference point, not gated).

The async MLP run also asserts the tentpole's counters: ZERO per-batch
host syncs (``loop_host_sync``) and ZERO steady-state recompiles
(``loop_recompile``) over the timed window.

Usage: python tools/perf/fit_loop_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np

FEAT = 2048
MLP_BATCH = 256
MLP_HIDDEN = 320
MLP_IO_MS = 12.0
STEM_BATCH = 64


def _mlp_symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=MLP_HIDDEN, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _stem_symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, num_filter=32, kernel=(7, 7),
                           stride=(2, 2), pad=(3, 3), name="conv0")
    bn = mx.sym.BatchNorm(c, name="bn0")
    r = mx.sym.Activation(bn, act_type="relu", name="relu0")
    p = mx.sym.Pooling(r, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max", name="pool0")
    f = mx.sym.Flatten(p, name="flat")
    fc = mx.sym.FullyConnected(f, num_hidden=10, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


class RecordPipeIter(object):
    """Record-pipeline stand-in: a fixed per-batch input latency (storage
    read / decode stall — sleeps with the GIL released, like real IO)
    followed by a numpy normalize pass. The async loop's prefetch worker
    absorbs both off the critical path; the sync loop pays them serially
    before every step."""

    def __init__(self, num_batches, batch_size, feat, num_classes=10,
                 io_ms=MLP_IO_MS, seed=0):
        import mxnet_tpu as mx
        self._mx = mx
        self.batch_size = batch_size
        self.num_batches = num_batches
        self.io_ms = io_ms
        rng = np.random.RandomState(seed)
        # a small raw pool re-normalized each batch (keeps memory flat)
        self._raw = rng.uniform(0, 255, (4, batch_size, feat)) \
            .astype(np.float32)
        self._labels = rng.randint(0, num_classes, (4, batch_size)) \
            .astype(np.float32)
        self.provide_data = [mx.io.DataDesc("data", (batch_size, feat))]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (batch_size,))]
        self.cur = 0

    def __iter__(self):
        return self

    def reset(self):
        self.cur = 0

    def next(self):
        if self.cur >= self.num_batches:
            raise StopIteration
        mx = self._mx
        i = self.cur % self._raw.shape[0]
        self.cur += 1
        time.sleep(self.io_ms / 1e3)      # storage/decode latency
        x = (np.clip(self._raw[i], 0.0, 255.0) - np.float32(127.5)) \
            / np.float32(58.0)
        return mx.io.DataBatch(data=[mx.nd.array(x)],
                               label=[mx.nd.array(self._labels[i])],
                               pad=0)

    def __next__(self):
        return self.next()


def _fit_once(mod, it, window):
    """One epoch through fit() under the given async window; returns
    (steps/s, counter deltas)."""
    from mxnet_tpu import config as cfg, profiler
    cfg.set("MXNET_TPU_ASYNC_WINDOW", window)
    try:
        with profiler.counter_delta() as d:
            t0 = time.perf_counter()
            mod.fit(it, eval_metric="acc", num_epoch=1,
                    optimizer="sgd",
                    optimizer_params={"learning_rate": 0.01})
            dt = time.perf_counter() - t0
        return it.num_batches / dt, d.all()
    finally:
        cfg.reset("MXNET_TPU_ASYNC_WINDOW")


def _bench_workload(symbol, it, repeats=3):
    import mxnet_tpu as mx
    mod = mx.mod.Module(symbol, context=mx.cpu())
    # warmup epoch compiles the fused step (and the metric reduce)
    _fit_once(mod, it, window=0)
    _fit_once(mod, it, window=2)
    sync_best = async_best = 0.0
    async_counters = {}
    for _ in range(repeats):
        s, _d = _fit_once(mod, it, window=0)
        sync_best = max(sync_best, s)
        a, d = _fit_once(mod, it, window=2)
        if a > async_best:
            async_best = a
            async_counters = d
    return {
        "sync_steps_s": round(sync_best, 2),
        "async_steps_s": round(async_best, 2),
        "speedup": round(async_best / sync_best, 3),
        "batches_per_epoch": it.num_batches,
        "host_syncs_per_batch": async_counters.get("loop_host_sync", 0)
        / it.num_batches,
        "steady_state_recompiles": async_counters.get("loop_recompile", 0),
        "prefetch_placed": async_counters.get("loop_prefetch_placed", 0),
        "window_waits": async_counters.get("loop_window_wait", 0),
        "metric_syncs": async_counters.get("loop_metric_sync", 0),
    }


class _ArrayIter(RecordPipeIter):
    """Compute-bound variant: the 'augment' is a single cheap slice, so
    the step dominates and async ~ sync."""

    def __init__(self, num_batches, batch_size, shape, num_classes=10,
                 seed=0):
        import mxnet_tpu as mx
        self._mx = mx
        self.batch_size = batch_size
        self.num_batches = num_batches
        rng = np.random.RandomState(seed)
        self._raw = rng.uniform(-1, 1, (4, batch_size) + shape) \
            .astype(np.float32)
        self._labels = rng.randint(0, num_classes, (4, batch_size)) \
            .astype(np.float32)
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size,) + shape)]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (batch_size,))]
        self.cur = 0

    def next(self):
        if self.cur >= self.num_batches:
            raise StopIteration
        mx = self._mx
        i = self.cur % self._raw.shape[0]
        self.cur += 1
        return mx.io.DataBatch(data=[mx.nd.array(self._raw[i])],
                               label=[mx.nd.array(self._labels[i])],
                               pad=0)


def run(quick=False):
    n_mlp = 15 if quick else 40
    n_stem = 6 if quick else 20
    repeats = 2 if quick else 3
    results = {}
    results["mlp"] = _bench_workload(
        _mlp_symbol(), RecordPipeIter(n_mlp, MLP_BATCH, FEAT),
        repeats=repeats)
    results["resnet_stem"] = _bench_workload(
        _stem_symbol(), _ArrayIter(n_stem, STEM_BATCH, (3, 32, 32)),
        repeats=repeats)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    results = run(quick=args.quick)
    payload = {"bench": "fit_loop", "results": results}
    out = json.dumps(payload, indent=2)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()

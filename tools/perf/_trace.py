"""Shared XLA trace-event aggregation for the perf profiling scripts."""
import collections
import glob
import gzip
import json
import os


def aggregate_trace(logdir, steps):
    """Aggregate a jax.profiler trace dir by op name.

    Returns rows sorted by descending device time:
    ``[(op, ms_per_step, calls_per_step, GBps), ...]``.
    """
    files = glob.glob(logdir + "/**/*.trace.json.gz", recursive=True)
    assert files, "no trace written under %s:\n%s" % (
        logdir, os.popen("find %s -type f" % logdir).read())
    ev = json.load(gzip.open(files[0]))["traceEvents"]
    agg = collections.defaultdict(lambda: [0.0, 0.0, 0])
    for e in ev:
        if e.get("ph") != "X" or "args" not in e:
            continue
        a = e["args"]
        if "device_duration_ps" not in a:
            continue
        dur = float(a["device_duration_ps"]) / 1e9  # ms
        op = a.get("tf_op", e.get("name", "?"))
        key = op.split("/")[-1] if "/" in op else op
        agg[key][0] += dur
        agg[key][1] += float(a.get("bytes_accessed", 0))
        agg[key][2] += 1
    rows = []
    for k, (d, by, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        d_step = d / steps
        bw = by / steps / (d_step / 1e3) / 1e9 if d_step > 0 else 0.0
        rows.append((k, d_step, n // steps, bw))
    return rows


def print_rows(rows, limit=30):
    print("%-52s %9s %6s %9s" % ("op", "ms/step", "n", "GB/s"))
    tot = 0.0
    for k, d_step, n, bw in rows[:limit]:
        tot += d_step
        print("%-52s %9.3f %6d %9.0f" % (k[:52], d_step, n, bw))
    print("TOTAL (top rows): %.1f ms/step" % tot)

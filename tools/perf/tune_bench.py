"""Autotuner bench: tuner winner vs the hand-tuned bench config
(ISSUE 19 acceptance — ``BENCH_tune.json``).

For each zoo net the search runs with the probe budget of a real
``fit(tune="auto")`` cold start. The DEFAULT candidate — exactly the
hand-tuned configuration ``bench.py`` runs (repo knob defaults: remat
off, scan auto, group update on, async window 2) — is always probed
first, so every record carries the honest head-to-head: the tuner's
winner and the hand-tuned baseline scored by the SAME obs probe
harness on the same machine. Recorded per net:

* ``default`` / ``winner`` — the two probe scores (mfu, steps/s);
* ``mfu_delta`` / ``steps_delta`` — winner over default;
* ``search_s`` — total search wall-clock, ``n_probed``/``n_pruned``.

The gate (``--check``): the tuner must strictly beat the hand-tuned
config on MFU for >= 2 nets, and every search must finish inside its
bounded wall-clock (probes carry per-subprocess deadlines; a config
that wedges scores failed and the partials stand).

Usage: python tools/perf/tune_bench.py [--quick] [--check] [--json P]
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

# CPU probes need an explicit MFU denominator (no device table entry)
os.environ.setdefault("MXNET_TPU_OBS_PEAK_FLOPS", "1e12")

NETS = ("mlp", "transformer", "resnet8")


def bench_net(name, batch, steps, max_probes, deadline_s):
    from mxnet_tpu.tune import search
    from mxnet_tpu.tune.__main__ import _zoo
    sym, data_shapes, label_shapes, dtypes = _zoo(name, batch)
    t0 = time.perf_counter()
    cfg = search(sym, data_shapes, label_shapes, optimizer="sgd",
                 mode="auto", probe_steps=steps,
                 probe_deadline_s=deadline_s, max_probes=max_probes,
                 data_dtypes=dtypes, use_store=False,
                 log=lambda m: print("  " + str(m), flush=True))
    wall = round(time.perf_counter() - t0, 2)

    def _pick(s):
        if not s:
            return None
        return {"mfu": s.get("mfu"), "steps_per_sec": s.get("steps_per_sec"),
                "wall_s": s.get("wall_s")}

    win, base = cfg.score, cfg.baseline
    rec = {
        "net": name, "batch": batch, "probe_steps": steps,
        "winner_knobs": cfg.candidate.to_dict(), "source": cfg.source,
        "winner": _pick(win), "default": _pick(base),
        "search_s": wall, "n_probed": cfg.n_probed,
        "n_pruned": cfg.n_pruned,
    }
    if win and base and base.get("mfu"):
        rec["mfu_delta"] = round(win["mfu"] / base["mfu"], 3)
        rec["steps_delta"] = round(
            win["steps_per_sec"] / base["steps_per_sec"], 3)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="2 nets, fewer probes")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero unless the tuner beats the "
                         "hand-tuned config on >= 2 nets")
    ap.add_argument("--json", default=None, help="write BENCH_tune.json")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--max-probes", type=int, default=4)
    ap.add_argument("--deadline", type=float, default=180.0)
    args = ap.parse_args()

    nets = NETS[:2] if args.quick else NETS
    records = []
    for name in nets:
        print("tune_bench: %s" % name, flush=True)
        batch = 8 if name == "transformer" else 32
        rec = bench_net(name, batch, args.steps,
                        2 if args.quick else args.max_probes,
                        args.deadline)
        records.append(rec)
        print("  winner=%s source=%s mfu_delta=%s search_s=%s"
              % (rec["winner_knobs"], rec["source"],
                 rec.get("mfu_delta"), rec["search_s"]), flush=True)

    beats = sum(1 for r in records
                if r.get("mfu_delta") and r["mfu_delta"] > 1.0)
    out = {
        "metric": "tune_search", "unit": "mfu_ratio_vs_hand_tuned",
        "nets": records,
        "nets_tuner_beats_hand_tuned": beats,
        "total_search_s": round(sum(r["search_s"] for r in records), 2),
    }
    print(json.dumps(out), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.check:
        ok = beats >= 2
        print("tune_bench gate: %s (tuner beats hand-tuned on %d nets)"
              % ("PASS" if ok else "FAIL", beats), flush=True)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Serving throughput: sequential batch-1 prediction vs the dynamic
batcher (mxnet_tpu/serve), closed-loop load generator.

Two models, the same pair the trainer-step bench uses:

* the doc-evidence MLP (Dense 128 relu -> Dense 10) — dispatch-bound,
  where batching pays the most;
* a small ResNet stem (conv/BN/pool/FC mix) — some real compute per
  request.

Protocol: ``C`` closed-loop clients (each submits one request, waits
for its result, repeats — the classic closed-loop load model) against
one InferenceServer; the baseline is ONE caller doing batch-1 forwards
back-to-back, i.e. exactly what today's ``Predictor`` offers concurrent
traffic once serialized. Reported per model: requests/sec both ways,
speedup, p50/p95/p99 latency under load, batch occupancy and the
compile count (must equal the touched bucket set — zero steady-state
recompiles).

Usage: python tools/perf/serve_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def _build_mlp():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    return net, (64,)


def _build_resnet_stem():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Conv2D(16, kernel_size=7, strides=2, padding=3),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.MaxPool2D(pool_size=3, strides=2, padding=1),
            nn.Flatten(),
            nn.Dense(10))
    return net, (3, 32, 32)


def _sequential_rps(net, xs, n_req):
    """One caller, batch-1 forwards back-to-back — the Predictor
    status quo for concurrent traffic."""
    import mxnet_tpu as mx
    # warmup / compile
    float(np.asarray(net(mx.nd.array(xs[0][None])).asnumpy()).sum())
    t0 = time.perf_counter()
    for i in range(n_req):
        out = net(mx.nd.array(xs[i % len(xs)][None]))
        out.asnumpy()                 # fence: latency the caller sees
    dt = time.perf_counter() - t0
    return n_req / dt


def _served_rps(net, xs, n_req, clients, max_batch):
    from mxnet_tpu import serve

    srv = serve.InferenceServer(net, max_batch_size=max_batch,
                                max_delay_us=2000,
                                name="serve_bench")
    try:
        # warm the batch-bucket grid so the timed window is steady-state
        for b in srv.buckets.batch_buckets:
            srv.submit(np.stack(xs[:1] * b), batched=True).result(60)
        compiles_warm = srv.stats()["compiles"]
        srv.latency.reset()     # warmup compiles are not serving latency
        per_client = n_req // clients
        errors = []

        def client(cid):
            try:
                for i in range(per_client):
                    srv.submit(xs[(cid + i * clients) % len(xs)]) \
                        .result(timeout=120)
            except Exception as exc:               # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise errors[0]
        stats = srv.stats()
        recompiles = stats["compiles"] - compiles_warm
        return per_client * clients / dt, stats, recompiles
    finally:
        srv.close()


def _bench_one(build, n_req, clients, max_batch):
    import mxnet_tpu as mx

    net, sample_shape = build()
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    xs = [rng.rand(*sample_shape).astype(np.float32) for _ in range(64)]
    net(mx.nd.array(xs[0][None]))     # shape probe

    seq_rps = _sequential_rps(net, xs, max(n_req // 4, 20))
    served_rps, stats, recompiles = _served_rps(net, xs, n_req, clients,
                                                max_batch)
    lat = stats["latency"] or {}
    return {
        "n_requests": n_req,
        "clients": clients,
        "max_batch": max_batch,
        "sequential_rps": round(seq_rps, 1),
        "served_rps": round(served_rps, 1),
        "speedup": round(served_rps / seq_rps, 2),
        "p50_ms": lat.get("p50_ms"),
        "p95_ms": lat.get("p95_ms"),
        "p99_ms": lat.get("p99_ms"),
        "avg_batch_rows": stats["avg_batch_rows"],
        "occupancy": stats["occupancy"],
        "bucket_compiles": stats["compiles"],
        "steady_state_recompiles": recompiles,
    }


# ===================================================================
# Generative decode: continuous batching vs sequential batch-1
# ===================================================================

_DECODE_GEO = dict(vocab_size=128, num_layers=2, d_model=32, n_heads=2,
                   seq_len=64)


def _build_decode_module(seed=11):
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(**_DECODE_GEO)
    mod = mx.mod.Module(net, context=mx.cpu())
    s = _DECODE_GEO["seq_len"]
    mod.bind(data_shapes=[("data", (1, s))],
             label_shapes=[("softmax_label", (1, s))])
    mx.random.seed(seed)
    mod.init_params(mx.init.Uniform(0.05))
    return mod


def _decode_prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, _DECODE_GEO["vocab_size"],
                             size=rng.randint(2, 12)))
            for _ in range(n)]


def _decode_closed_loop(mod, clients, n_req, new_tokens, max_sequences):
    """``clients`` closed-loop generators against one GenerativeServer;
    returns (tok/s, ttft snapshot, tpot snapshot, steady recompiles,
    executable bound). ``max_sequences=1`` with ``clients=1`` IS the
    sequential batch-1 baseline — same engine, no co-residency."""
    from mxnet_tpu import profiler, serve
    name = "dbench%d_%d" % (clients, max_sequences)
    srv = serve.GenerativeServer(mod, n_heads=_DECODE_GEO["n_heads"],
                                 max_sequences=max_sequences, page=16,
                                 int8=False, queue_bound=4 * clients + 8,
                                 name=name)
    prompts = _decode_prompts(64)
    try:
        # warmup wave: the LONGEST prompt in the pool decodes to the
        # deepest position any timed request reaches, so every
        # prompt/decode bucket is compiled before the timed window —
        # one stray bucket compile (~400ms) would otherwise dominate a
        # sub-second measurement
        longest = max(prompts, key=len)
        warm = [srv.submit_generate(longest, max_new_tokens=new_tokens)
                for _ in range(min(clients, max_sequences) or 1)]
        for h in warm:
            h.result(timeout=300)
        compiles_warm = profiler.get_counter(name + "_compile")
        srv.latency.reset()
        per_client = max(n_req // clients, 1)
        tokens_out = [0] * clients
        errors = []

        def client(cid):
            try:
                for i in range(per_client):
                    h = srv.submit_generate(
                        prompts[(cid + i * clients) % len(prompts)],
                        max_new_tokens=new_tokens)
                    tokens_out[cid] += len(h.result(timeout=300))
            except Exception as exc:               # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise errors[0]
        st = srv.stats()
        recompiles = profiler.get_counter(name + "_compile") - compiles_warm
        return (sum(tokens_out) / dt, st["ttft"], st["tpot"], recompiles,
                st["executable_bound"])
    finally:
        srv.close()


_COLD_START_SCRIPT = r"""
import os, sys, time, json
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, %(root)r)
t_proc = time.perf_counter()
import mxnet_tpu as mx
from mxnet_tpu.models import transformer
net = transformer.get_symbol(**%(geo)r)
mod = mx.mod.Module(net, context=mx.cpu())
s = %(geo)r["seq_len"]
mod.bind(data_shapes=[("data", (1, s))],
         label_shapes=[("softmax_label", (1, s))])
mx.random.seed(11)
mod.init_params(mx.init.Uniform(0.05))
srv = mx.serve.GenerativeServer(mod, n_heads=%(geo)r["n_heads"],
                                max_sequences=4, page=16, int8=False,
                                name="coldbench")
t0 = time.perf_counter()
h = srv.submit_generate([3, 1, 4, 1, 5], max_new_tokens=4)
first = next(iter(h))
ttft = time.perf_counter() - t0
h.result(timeout=300)
srv.close()
snap = mx.obs.report()
backend = len([c for c in snap["compiles"] if c.get("scope") == "coldbench"])
print(json.dumps({"ttft_s": ttft, "backend_compiles": backend,
                  "proc_s": time.perf_counter() - t_proc}))
"""


def _cold_start_ttft(cache_dir=None):
    """Fresh process -> first generated token, with/without the
    executable cache. Returns the subprocess's own measurement."""
    import subprocess
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if cache_dir is None:
        env.pop("MXNET_TPU_COMPILE_CACHE", None)
    else:
        env["MXNET_TPU_COMPILE_CACHE"] = cache_dir
    code = _COLD_START_SCRIPT % {"root": os.path.abspath(root),
                                 "geo": _DECODE_GEO}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError("cold-start probe failed:\n" + out.stderr)
    return json.loads(out.stdout.strip().splitlines()[-1])


def _bench_decode(quick=False, reps=1):
    """The ISSUE 16 acceptance table: aggregate tok/s continuous vs
    sequential batch-1, TTFT/TPOT percentiles, zero steady-state
    recompiles, cold-start-to-first-token with and without the
    executable cache."""
    mod = _build_decode_module()
    new_tokens = 8 if quick else 16
    client_loads = [8] if quick else [8, 32]
    out = {"new_tokens_per_request": new_tokens,
           "geometry": dict(_DECODE_GEO)}

    # baseline: batch-1 SCHEDULING on the SAME deployment — one
    # closed-loop client against the identical 32-slot server, so the
    # cache geometry and executable set match and the comparison
    # isolates the scheduling policy (the Orca/vLLM experimental
    # control), not a smaller cache's cheaper step
    seq_tps = 0.0
    for _ in range(reps):
        tps, _, _, _, _ = _decode_closed_loop(
            mod, clients=1, n_req=4 if quick else 12,
            new_tokens=new_tokens, max_sequences=32)
        seq_tps = max(seq_tps, tps)
    out["sequential_tps"] = round(seq_tps, 1)

    for clients in client_loads:
        best = None
        for _ in range(reps):
            tps, ttft, tpot, recompiles, bound = _decode_closed_loop(
                mod, clients=clients,
                n_req=2 * clients if quick else 3 * clients,
                new_tokens=new_tokens, max_sequences=32)
            if best is None or tps > best["tps"]:
                best = {"tps": tps, "ttft": ttft, "tpot": tpot,
                        "recompiles": recompiles, "bound": bound}
        assert best["recompiles"] == 0, (
            "steady-state decode recompiled %d times" % best["recompiles"])
        out["clients_%d" % clients] = {
            "continuous_tps": round(best["tps"], 1),
            "speedup_vs_sequential": round(best["tps"] / seq_tps, 2),
            "ttft": best["ttft"],
            "tpot": best["tpot"],
            "steady_state_recompiles": best["recompiles"],
            "executable_bound": best["bound"],
        }
        print("decode c=%-3d seq %7.1f tok/s  continuous %8.1f tok/s  "
              "%5.2fx  ttft p50 %s ms  tpot p50 %s ms  recompiles %d"
              % (clients, seq_tps, best["tps"], best["tps"] / seq_tps,
                 (best["ttft"] or {}).get("p50_ms"),
                 (best["tpot"] or {}).get("p50_ms"),
                 best["recompiles"]))

    if not quick:
        import tempfile
        cold = _cold_start_ttft(cache_dir=None)
        cache_dir = tempfile.mkdtemp(prefix="serve_bench_aot_")
        _cold_start_ttft(cache_dir=cache_dir)       # populate
        warm = _cold_start_ttft(cache_dir=cache_dir)
        assert warm["backend_compiles"] == 0, (
            "AOT warm restart still compiled %d serve programs"
            % warm["backend_compiles"])
        out["cold_start"] = {
            "no_cache_ttft_s": round(cold["ttft_s"], 3),
            "compile_cache_ttft_s": round(warm["ttft_s"], 3),
            "compile_cache_backend_compiles": warm["backend_compiles"],
        }
        print("decode cold-start ttft: %.3fs uncached -> %.3fs with "
              "MXNET_TPU_COMPILE_CACHE (0 backend compiles)"
              % (cold["ttft_s"], warm["ttft_s"]))
    return out


# ===================================================================
# Fleet: multi-replica gateway scaling + kill-one-under-load
# ===================================================================

_FLEET_STEP_MS = 20.0
_FLEET_SLOTS = 8
_FLEET_NEW_TOKENS = 32


def _fleet_closed_loop(gw, clients, n_req, new_tokens):
    """``clients`` closed-loop generators against one Gateway; returns
    (aggregate tok/s, gateway stats snapshot)."""
    prompts = _decode_prompts(64)
    tokens_out = [0] * clients
    errors = []

    def client(cid):
        try:
            per = max(n_req // clients, 1)
            for i in range(per):
                h = gw.submit_generate(
                    prompts[(cid + i * clients) % len(prompts)],
                    max_new_tokens=new_tokens)
                tokens_out[cid] += len(h.result(timeout=600))
        except Exception as exc:                           # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return sum(tokens_out) / dt, gw.stats()


def _fleet_scaling(quick=False):
    """Aggregate tok/s and gateway TTFT for 1/2/3 DEVICE-PACED replicas
    at matched per-replica deployments (1xS vs 2xS vs 3xS slots).

    Replicas are real subprocesses behind the real wire, but their
    decode step is the scripted simulator's timed wait — the TPU regime
    where the device does the work and the host idles between steps.
    The host-side fleet fabric (gateway scheduler, routing, sockets,
    per-token frame handling) is measured for real; only device time is
    simulated. On this device-less bench host a REAL model's decode
    step is host CPU, so N co-resident replica processes just split one
    core N ways — that anti-scaling measures the box, not the gateway
    (recorded honestly in the ``real_model`` section)."""
    from mxnet_tpu.fleet import Gateway
    spec = {"kind": "scripted", "slots": _FLEET_SLOTS,
            "step_ms": _FLEET_STEP_MS, "prefill_ms_per_token": 1.0,
            "name": "benchrep"}
    new_tokens = 16 if quick else _FLEET_NEW_TOKENS
    out = {
        "mode": "device_paced_scripted_replicas",
        "pacing": {"step_ms": _FLEET_STEP_MS,
                   "slots_per_replica": _FLEET_SLOTS,
                   "new_tokens_per_request": new_tokens,
                   "device_paced_ceiling_tps_per_replica": round(
                       _FLEET_SLOTS / (_FLEET_STEP_MS / 1e3), 1)},
    }
    base_tps = None
    for n in ((1, 2) if quick else (1, 2, 3)):
        gw = Gateway(spec=spec, replicas=n, port=None, stats_period=0.2,
                     name="bench_fleet%d" % n)
        try:
            live = gw.wait_ready(n, timeout=300.0)
            assert live == n, "only %d/%d replicas live" % (live, n)
            clients = 2 * _FLEET_SLOTS * n
            n_req = (2 if quick else 4) * clients
            tps, st = _fleet_closed_loop(gw, clients, n_req, new_tokens)
        finally:
            gw.close(drain=False, timeout=60.0)
        rec = {"replicas": n, "clients": clients,
               "aggregate_tps": round(tps, 1),
               "ttft": st["ttft"], "tpot": st["tpot"],
               "failover": st["failover"], "shed": st["shed"]}
        if base_tps is None:
            base_tps = tps
        else:
            rec["speedup_vs_1_replica"] = round(tps / base_tps, 2)
        out["replicas_%d" % n] = rec
        print("fleet r=%d  %8.1f tok/s  %s  ttft p50 %s ms p99 %s ms"
              % (n, tps,
                 ("%.2fx" % (tps / base_tps)) if n > 1 else "  1x ",
                 (st["ttft"] or {}).get("p50_ms"),
                 (st["ttft"] or {}).get("p99_ms")))
    ratio = out["replicas_2"]["speedup_vs_1_replica"]
    assert ratio >= 1.6, (
        "2-replica aggregate only %.2fx of 1 replica on matched "
        "per-replica deployments (want >= 1.6x)" % ratio)
    return out


def _fleet_kill_under_load():
    """REAL model replicas: kill one mid-stream under load; record
    recovery time and assert zero token duplication (every stream
    bit-equal to a single-server reference)."""
    import os as _os
    import signal as _signal
    import tempfile as _tempfile
    from mxnet_tpu.fleet import Gateway
    from mxnet_tpu.fleet.replica import build_from_spec
    geo = dict(_DECODE_GEO, seq_len=32)
    spec = {"kind": "transformer", "geo": geo, "seed": 11, "slots": 4,
            "page": 8, "name": "benchkill"}
    _os.environ["MXNET_TPU_COMPILE_CACHE"] = _tempfile.mkdtemp(
        prefix="fleet_bench_aot_")
    ref_srv = build_from_spec(dict(spec, name="benchkillref"))
    prompts = [[3, 1, 4], [1, 5, 9], [2, 6], [5, 3, 5],
               [8, 9, 7], [3, 2], [7, 7, 1], [9, 4]]
    new_tokens = 12
    try:
        ref = {tuple(p): ref_srv.submit_generate(
                   p, max_new_tokens=new_tokens).result(timeout=600)
               for p in prompts}
    finally:
        ref_srv.close()
    gw = Gateway(spec=spec, replicas=2, port=None, stats_period=0.2,
                 name="bench_kill")
    try:
        assert gw.wait_ready(2, timeout=600.0) == 2
        handles = [(p, gw.submit_generate(p, max_new_tokens=new_tokens))
                   for p in prompts]
        # kill a replica once streams are moving
        deadline = time.perf_counter() + 60
        victim_pid = None
        while time.perf_counter() < deadline and victim_pid is None:
            st = gw.stats()
            for r in st["replicas"]:
                if r["assigned"] > 0 and r["stats"].get("pid"):
                    victim_pid = r["stats"]["pid"]
                    break
            time.sleep(0.02)
        assert victim_pid, "no replica ever took load"
        t_kill = time.perf_counter()
        _os.kill(victim_pid, _signal.SIGKILL)
        dup_tokens = 0
        for p, h in handles:
            got = h.result(timeout=600)
            assert got == ref[tuple(p)], \
                "stream for %s diverged after the kill" % (p,)
        recovery_s = time.perf_counter() - t_kill
        st = gw.stats()
        assert st["dup_dropped"] == 0, st["dup_dropped"]
        heal_deadline = time.perf_counter() + 300
        while time.perf_counter() < heal_deadline \
                and gw.stats()["live"] < 2:
            time.sleep(0.2)
        respawn_s = time.perf_counter() - t_kill
        rec = {
            "replicas": 2, "in_flight_at_kill": len(prompts),
            "all_streams_complete_after_kill_s": round(recovery_s, 3),
            "respawned_to_full_strength_s": round(respawn_s, 3),
            "failover": st["failover"],
            "duplicated_tokens": dup_tokens + st["dup_dropped"],
            "streams_bit_equal_to_reference": True,
        }
        print("fleet kill drill: %d streams recovered in %.2fs, world "
              "healed in %.2fs, 0 duplicated tokens"
              % (len(prompts), recovery_s, respawn_s))
        return rec
    finally:
        gw.close(drain=False, timeout=60.0)


def _fleet_real_model_record():
    """The honest number: real-model replicas on THIS host. Decode here
    is host-CPU-bound (no device), so replica processes contend for the
    same core and aggregate throughput does NOT scale — recorded as-is
    with the reason, next to the device-paced table that models the TPU
    regime."""
    from mxnet_tpu.fleet import Gateway
    from mxnet_tpu.fleet.replica import build_from_spec
    geo = dict(_DECODE_GEO, seq_len=32)
    spec = {"kind": "transformer", "geo": geo, "seed": 11, "slots": 4,
            "page": 8, "name": "benchreal"}
    new_tokens, n_req = 12, 24
    solo = build_from_spec(dict(spec, name="benchrealsolo"))
    prompts = _decode_prompts(16)
    try:
        done = 0
        t0 = time.perf_counter()
        hs = [solo.submit_generate(prompts[i % len(prompts)],
                                   max_new_tokens=new_tokens)
              for i in range(n_req)]
        for h in hs:
            done += len(h.result(timeout=600))
        solo_tps = done / (time.perf_counter() - t0)
    finally:
        solo.close()
    gw = Gateway(spec=spec, replicas=2, port=None, stats_period=0.2,
                 name="bench_real")
    try:
        assert gw.wait_ready(2, timeout=600.0) == 2
        fleet_tps, _ = _fleet_closed_loop(gw, clients=8, n_req=n_req,
                                          new_tokens=new_tokens)
    finally:
        gw.close(drain=False, timeout=60.0)
    rec = {
        "single_server_tps": round(solo_tps, 1),
        "fleet_2_replica_tps": round(fleet_tps, 1),
        "ratio": round(fleet_tps / solo_tps, 2),
        "note": ("decode on this bench host is CPU-bound (no "
                 "accelerator), so the ratio measures host scheduling "
                 "across 2 replica processes sharing the same cores, "
                 "not device scaling; the device_paced table above "
                 "models the TPU regime where the device decodes and "
                 "the host-side fleet fabric is the measured part"),
    }
    print("fleet real-model (host-CPU-bound): solo %.1f tok/s vs "
          "2-replica %.1f tok/s (%.2fx) — see note"
          % (solo_tps, fleet_tps, rec["ratio"]))
    return rec


def _bench_fleet(quick=False):
    """The ISSUE 20 acceptance table: aggregate tok/s + TTFT p50/95/99
    for 1/2/3 replicas at matched per-replica deployments, the
    kill-one-replica-under-load record (recovery time, zero token
    duplication), and the honest real-model record for this host."""
    from mxnet_tpu import config as _config
    _config.set("MXNET_TPU_FLEET", True)
    _config.set("MXNET_TPU_ELASTIC_BACKOFF", 0.2)
    out = _fleet_scaling(quick=quick)
    out["kill_under_load"] = _fleet_kill_under_load()
    if not quick:
        out["real_model"] = _fleet_real_model_record()
    return out


def run(quick=False, reps=1):
    n_req = 400 if quick else 4000
    clients = 16 if quick else 32
    max_batch = 32
    results = {}
    models = [("mlp", _build_mlp)]
    if not quick:
        models.append(("resnet_stem", _build_resnet_stem))
    for name, build in models:
        # best-of-reps, same policy as trainer_step_bench: this shared
        # host's available CPU swings ~3x run to run, so a single rep
        # measures the box, not the batcher. Sequential and served each
        # keep their own best (both sides at box-best is the fair pair).
        r = None
        best_seq = 0.0
        for _ in range(reps):
            cur = _bench_one(build, n_req, clients, max_batch)
            best_seq = max(best_seq, cur["sequential_rps"])
            if r is None or cur["served_rps"] > r["served_rps"]:
                r = cur
        r["sequential_rps"] = best_seq
        r["speedup"] = round(r["served_rps"] / best_seq, 2)
        r["reps"] = reps
        results[name] = r
        print("%-12s seq %8.1f req/s   served %8.1f req/s   %5.2fx   "
              "p50 %s ms  p99 %s ms  occ %s"
              % (name, r["sequential_rps"], r["served_rps"], r["speedup"],
                 r["p50_ms"], r["p99_ms"], r["occupancy"]))
    results["decode"] = _bench_decode(quick=quick, reps=reps)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fast smoke variant (fewer requests, MLP only)")
    ap.add_argument("--reps", type=int, default=1,
                    help="repetitions; best throughput per side is kept")
    ap.add_argument("--json", default=None, help="write results to PATH")
    ap.add_argument("--decode-only", action="store_true",
                    help="run only the generative-decode section")
    ap.add_argument("--decode-json", default=None,
                    help="write the decode section to PATH "
                         "(BENCH_decode.json)")
    ap.add_argument("--fleet", action="store_true",
                    help="run only the multi-replica fleet section")
    ap.add_argument("--fleet-json", default=None,
                    help="write the fleet section to PATH "
                         "(BENCH_fleet.json)")
    args = ap.parse_args()
    if args.fleet:
        results = {"fleet": _bench_fleet(quick=args.quick)}
    elif args.decode_only:
        results = {"decode": _bench_decode(quick=args.quick,
                                           reps=args.reps)}
    else:
        results = run(quick=args.quick, reps=args.reps)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"bench": "serving", "results": results}, f,
                      indent=2)
        print("wrote", args.json)
    if args.decode_json:
        payload = dict(results["decode"])
        payload["bench"] = "serve_decode"
        payload["reps"] = args.reps
        with open(args.decode_json, "w") as f:
            json.dump(payload, f, indent=2)
        print("wrote", args.decode_json)
    if args.fleet_json:
        payload = dict(results["fleet"])
        payload["bench"] = "fleet"
        with open(args.fleet_json, "w") as f:
            json.dump(payload, f, indent=2)
        print("wrote", args.fleet_json)
    return results


if __name__ == "__main__":
    main()

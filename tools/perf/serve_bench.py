"""Serving throughput: sequential batch-1 prediction vs the dynamic
batcher (mxnet_tpu/serve), closed-loop load generator.

Two models, the same pair the trainer-step bench uses:

* the doc-evidence MLP (Dense 128 relu -> Dense 10) — dispatch-bound,
  where batching pays the most;
* a small ResNet stem (conv/BN/pool/FC mix) — some real compute per
  request.

Protocol: ``C`` closed-loop clients (each submits one request, waits
for its result, repeats — the classic closed-loop load model) against
one InferenceServer; the baseline is ONE caller doing batch-1 forwards
back-to-back, i.e. exactly what today's ``Predictor`` offers concurrent
traffic once serialized. Reported per model: requests/sec both ways,
speedup, p50/p95/p99 latency under load, batch occupancy and the
compile count (must equal the touched bucket set — zero steady-state
recompiles).

Usage: python tools/perf/serve_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def _build_mlp():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    return net, (64,)


def _build_resnet_stem():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Conv2D(16, kernel_size=7, strides=2, padding=3),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.MaxPool2D(pool_size=3, strides=2, padding=1),
            nn.Flatten(),
            nn.Dense(10))
    return net, (3, 32, 32)


def _sequential_rps(net, xs, n_req):
    """One caller, batch-1 forwards back-to-back — the Predictor
    status quo for concurrent traffic."""
    import mxnet_tpu as mx
    # warmup / compile
    float(np.asarray(net(mx.nd.array(xs[0][None])).asnumpy()).sum())
    t0 = time.perf_counter()
    for i in range(n_req):
        out = net(mx.nd.array(xs[i % len(xs)][None]))
        out.asnumpy()                 # fence: latency the caller sees
    dt = time.perf_counter() - t0
    return n_req / dt


def _served_rps(net, xs, n_req, clients, max_batch):
    from mxnet_tpu import serve

    srv = serve.InferenceServer(net, max_batch_size=max_batch,
                                max_delay_us=2000,
                                name="serve_bench")
    try:
        # warm the batch-bucket grid so the timed window is steady-state
        for b in srv.buckets.batch_buckets:
            srv.submit(np.stack(xs[:1] * b), batched=True).result(60)
        compiles_warm = srv.stats()["compiles"]
        srv.latency.reset()     # warmup compiles are not serving latency
        per_client = n_req // clients
        errors = []

        def client(cid):
            try:
                for i in range(per_client):
                    srv.submit(xs[(cid + i * clients) % len(xs)]) \
                        .result(timeout=120)
            except Exception as exc:               # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise errors[0]
        stats = srv.stats()
        recompiles = stats["compiles"] - compiles_warm
        return per_client * clients / dt, stats, recompiles
    finally:
        srv.close()


def _bench_one(build, n_req, clients, max_batch):
    import mxnet_tpu as mx

    net, sample_shape = build()
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    xs = [rng.rand(*sample_shape).astype(np.float32) for _ in range(64)]
    net(mx.nd.array(xs[0][None]))     # shape probe

    seq_rps = _sequential_rps(net, xs, max(n_req // 4, 20))
    served_rps, stats, recompiles = _served_rps(net, xs, n_req, clients,
                                                max_batch)
    lat = stats["latency"] or {}
    return {
        "n_requests": n_req,
        "clients": clients,
        "max_batch": max_batch,
        "sequential_rps": round(seq_rps, 1),
        "served_rps": round(served_rps, 1),
        "speedup": round(served_rps / seq_rps, 2),
        "p50_ms": lat.get("p50_ms"),
        "p95_ms": lat.get("p95_ms"),
        "p99_ms": lat.get("p99_ms"),
        "avg_batch_rows": stats["avg_batch_rows"],
        "occupancy": stats["occupancy"],
        "bucket_compiles": stats["compiles"],
        "steady_state_recompiles": recompiles,
    }


def run(quick=False, reps=1):
    n_req = 400 if quick else 4000
    clients = 16 if quick else 32
    max_batch = 32
    results = {}
    models = [("mlp", _build_mlp)]
    if not quick:
        models.append(("resnet_stem", _build_resnet_stem))
    for name, build in models:
        # best-of-reps, same policy as trainer_step_bench: this shared
        # host's available CPU swings ~3x run to run, so a single rep
        # measures the box, not the batcher. Sequential and served each
        # keep their own best (both sides at box-best is the fair pair).
        r = None
        best_seq = 0.0
        for _ in range(reps):
            cur = _bench_one(build, n_req, clients, max_batch)
            best_seq = max(best_seq, cur["sequential_rps"])
            if r is None or cur["served_rps"] > r["served_rps"]:
                r = cur
        r["sequential_rps"] = best_seq
        r["speedup"] = round(r["served_rps"] / best_seq, 2)
        r["reps"] = reps
        results[name] = r
        print("%-12s seq %8.1f req/s   served %8.1f req/s   %5.2fx   "
              "p50 %s ms  p99 %s ms  occ %s"
              % (name, r["sequential_rps"], r["served_rps"], r["speedup"],
                 r["p50_ms"], r["p99_ms"], r["occupancy"]))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fast smoke variant (fewer requests, MLP only)")
    ap.add_argument("--reps", type=int, default=1,
                    help="repetitions; best throughput per side is kept")
    ap.add_argument("--json", default=None, help="write results to PATH")
    args = ap.parse_args()
    results = run(quick=args.quick, reps=args.reps)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"bench": "serving", "results": results}, f,
                      indent=2)
        print("wrote", args.json)
    return results


if __name__ == "__main__":
    main()

"""Measurements cited by docs/architecture/* (round 5).

Three numbers the design notes assert and should prove:
1. buffer donation: compiled argument/output aliasing and temp memory
   of the fused train step with vs without donated params
2. remat: compiled temp memory of the transformer step with vs without
   MXNET_EXEC_ENABLE_REMAT
3. fused step vs eager dispatch: same MLP trained via Module._fit_step
   (one jitted program) vs an eager per-op loop
Runs on the CPU backend (memory analysis is layout-exact there too).
"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import mxnet_tpu as mx


def main():
    # ---- 1: fused step vs eager per-op training loop, same MLP
    # (runs FIRST: the memory-analysis section leaves two
    # transformer Modules resident, which skews timings on the
    # 1-core host)
    rng = np.random.RandomState(0)
    X = rng.randn(256, 64).astype(np.float32)
    Y = rng.randint(0, 10, (256,)).astype(np.float32)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    sym = mx.sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (256, 64))],
             label_shapes=[("softmax_label", (256,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    db = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    mod._fit_step(db)
    t0 = time.perf_counter()
    for _ in range(100):
        mod._fit_step(db)
    mod.get_params()
    fused = 100 / (time.perf_counter() - t0)

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    xb, yb = mx.nd.array(X), mx.nd.array(Y)
    for _ in range(3):
        with mx.autograd.record():
            loss = mx.nd.mean(sce(net(xb), yb))
        loss.backward()
        tr.step(1)
    t0 = time.perf_counter()
    for _ in range(30):
        with mx.autograd.record():
            loss = mx.nd.mean(sce(net(xb), yb))
        loss.backward()
        tr.step(1)
    # fence on an UPDATED PARAMETER, not the last loss: the final
    # backward+update dispatch asynchronously and the loss value does
    # not depend on them (rig note: mis-fencing is the classic trap)
    w = list(net.collect_params().values())[0].data()
    float(np.asarray(w.asnumpy()).ravel()[0])
    eager = 30 / (time.perf_counter() - t0)

    # third tier: the same loop with the compiled-backward cache
    # disabled = the purely per-op eager baseline
    from mxnet_tpu import autograd as _ag
    _orig = _ag._compiled_backward
    _ag._compiled_backward = lambda *a, **k: (_ for _ in ()).throw(
        _ag._Uncacheable("disabled for baseline"))
    try:
        for _ in range(3):
            with mx.autograd.record():
                loss = mx.nd.mean(sce(net(xb), yb))
            loss.backward()
            tr.step(1)
        t0 = time.perf_counter()
        for _ in range(15):
            with mx.autograd.record():
                loss = mx.nd.mean(sce(net(xb), yb))
            loss.backward()
            tr.step(1)
        w = list(net.collect_params().values())[0].data()
        float(np.asarray(w.asnumpy()).ravel()[0])
        eager_nocache = 15 / (time.perf_counter() - t0)
    finally:
        _ag._compiled_backward = _orig
    print("fused step: %.0f steps/s   eager+cached-bwd: %.1f steps/s   "
          "eager-nocache: %.1f steps/s" % (fused, eager, eager_nocache))



    # ---- 2+3: memory analysis of the real fused step under flags
    from mxnet_tpu.models import transformer
    for tag, env in (("baseline", {}),
                     ("remat", {"MXNET_EXEC_ENABLE_REMAT": "1"})):
        for k, v in env.items():
            os.environ[k] = v
        mx.config.reset("MXNET_EXEC_ENABLE_REMAT")
        sym = transformer.get_symbol(vocab_size=512, num_layers=6,
                                     d_model=256, n_heads=8, seq_len=256)
        mod = mx.mod.Module(sym, context=mx.cpu(0))
        mod.bind(data_shapes=[("data", (16, 256))],
                 label_shapes=[("softmax_label", (16, 256))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)
        db = mx.io.DataBatch(
            data=[mx.nd.array(rng.randint(0, 512, (16, 256))
                              .astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 512, (16, 256))
                               .astype(np.float32))])
        mod._fit_step(db)
        # reach the jitted step and re-lower it with the live arguments
        # to read XLA's memory analysis
        import jax as _jax
        ex = mod._exec
        params = {n: ex.arg_dict[n].data for n in mod._param_names}
        states = mod._fused_states
        aux = {n: a.data for n, a in ex.aux_dict.items()}
        inputs = {n: ex.arg_dict[n].data
                  for n in ("data", "softmax_label")}
        comp = mod._fused_jit.lower(
            params, states, aux, inputs, {}, _jax.random.PRNGKey(0),
            jnp.asarray(0.1, jnp.float32),
            jnp.asarray(1, jnp.int32)).compile()
        ma = comp.memory_analysis()
        print("%s: temp %.2f MB  args %.2f MB  out %.2f MB  "
              "alias %.2f MB" % (
                  tag, ma.temp_size_in_bytes / 1e6,
                  ma.argument_size_in_bytes / 1e6,
                  ma.output_size_in_bytes / 1e6,
                  getattr(ma, "alias_size_in_bytes", 0) / 1e6))
        for k in env:
            del os.environ[k]
        mx.config.reset("MXNET_EXEC_ENABLE_REMAT")

if __name__ == "__main__":
    main()

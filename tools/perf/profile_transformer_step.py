"""Per-op profile of the L12 transformer fused train step (bench config):
where do the flash kernels' 30 ms go vs the 13 ms standalone ideal?"""
import sys, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu.models import transformer

L, D, H, T, V, B = 12, 2048, 16, 1024, 32000, 8
mx.amp.init("bfloat16")   # bench.py parity: bf16 compute, f32 master
sym = transformer.get_symbol(vocab_size=V, num_layers=L, d_model=D,
                             n_heads=H, seq_len=T, attention="flash")
mod = mx.mod.Module(sym, context=mx.tpu(0))
mod.bind(data_shapes=[("data", (B, T))],
         label_shapes=[("softmax_label", (B, T))])
mod.init_params(mx.init.Xavier())
mod.init_optimizer(optimizer="sgd",
                   optimizer_params={"learning_rate": 0.01})
rng = np.random.RandomState(0)
db = mx.io.DataBatch(
    data=[mx.nd.array(rng.randint(0, V, (B, T)).astype(np.float32), ctx=mx.tpu(0))],
    label=[mx.nd.array(rng.randint(0, V, (B, T)).astype(np.float32), ctx=mx.tpu(0))])

def drain():
    return float(np.asarray(mod._exec.arg_dict["lm_head_weight"].data[0, 0]))

for _ in range(2):
    mod._fit_step(db)
drain()

logdir = "/tmp/tf_prof"
os.system("rm -rf " + logdir)
STEPS = 4
with jax.profiler.trace(logdir):
    for _ in range(STEPS):
        mod._fit_step(db)
    drain()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _trace import aggregate_trace, print_rows

print_rows(aggregate_trace(logdir, STEPS))

"""Trainer.step: eager per-param dispatch vs the fused whole-model update.

Measures optimizer-step throughput (steps/s) on two models:

* the doc-evidence MLP (Dense 128 relu -> Dense 10; 4 params) — the same
  network tools/perf/doc_evidence.py uses for the fused-fit numbers;
* a small ResNet stem (7x7/2 conv + BatchNorm + Dense head; conv/BN/FC
  param mix, 8 params).

Gradients are produced once with a real forward/backward; the timed loop
then re-applies ``trainer.step`` so the number isolates the update path:
eager = one engine dispatch chain per parameter (the reference KVStore
push/pull + per-index Updater regime), fused = ONE structure-cached jitted
program per step (MXNET_TPU_FUSED_TRAINER, mxnet_tpu/_fused.py).

Usage: python tools/perf/trainer_step_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def _build_mlp():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    return net, (32, 64)


def _build_resnet_stem():
    from mxnet_tpu.gluon import nn
    net = nn.Sequential()
    net.add(nn.Conv2D(16, kernel_size=7, strides=2, padding=3),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.MaxPool2D(pool_size=3, strides=2, padding=1),
            nn.Flatten(),
            nn.Dense(10))
    return net, (8, 3, 32, 32)


def _bench_one(build, optimizer, fused, n_steps, opt_kwargs=None):
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import config as cfg

    cfg.set("MXNET_TPU_FUSED_TRAINER", fused)
    try:
        net, in_shape = build()
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), optimizer,
                                dict(opt_kwargs or {}, learning_rate=0.05))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.rand(*in_shape).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 10, (in_shape[0],)))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        n_params = sum(1 for p in net.collect_params().values()
                       if p.grad_req != "null")
        for _ in range(3):
            trainer.step(in_shape[0])   # warmup (compile + steady state)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            trainer.step(in_shape[0])
        # sync: include all queued device work in the measurement
        next(iter(net.collect_params().values())).data().asnumpy()
        dt = time.perf_counter() - t0
        return n_steps / dt, n_params
    finally:
        cfg.reset("MXNET_TPU_FUSED_TRAINER")


def run(quick=False, reps=1):
    n = 50 if quick else 400
    results = {}
    models = [("mlp", _build_mlp)]
    if not quick:
        models.append(("resnet_stem", _build_resnet_stem))
    for model_name, build in models:
        for opt_name, kw in [("sgd", {"momentum": 0.9}), ("adam", {})]:
            # best-of-reps: shared/loaded hosts make single runs noisy
            eager = fused = 0.0
            n_params = 0
            for _ in range(reps):
                e, n_params = _bench_one(build, opt_name, False, n, kw)
                f, _ = _bench_one(build, opt_name, True, n, kw)
                eager, fused = max(eager, e), max(fused, f)
            key = "%s_%s" % (model_name, opt_name)
            results[key] = {
                "n_params": n_params,
                "eager_steps_per_s": round(eager, 1),
                "fused_steps_per_s": round(fused, 1),
                "speedup": round(fused / eager, 2),
            }
            print("%-22s %2d params  eager %8.1f steps/s   fused %8.1f "
                  "steps/s   %5.2fx" % (key, n_params, eager, fused,
                                        fused / eager))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fast smoke variant (fewer steps, MLP only)")
    ap.add_argument("--reps", type=int, default=1,
                    help="repetitions; best throughput per config is kept")
    ap.add_argument("--json", default=None, help="write results to PATH")
    args = ap.parse_args()
    results = run(quick=args.quick, reps=args.reps)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"bench": "trainer_step", "results": results}, f,
                      indent=2)
        print("wrote", args.json)
    return results


if __name__ == "__main__":
    main()

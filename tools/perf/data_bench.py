"""mx.data throughput bench: img/s vs worker count on a decode-bound
pipeline (ISSUE 17 — the streaming data plane).

The pipeline is made decode-bound with ``StallTransform``: a fixed
per-record stall emulating remote-storage fetch / decode latency. This
is deliberate — CI boxes for this repo have ONE cpu core, so a
cpu-bound decode cannot scale with processes there (numpy decode is
serialized on the core); latency-bound decode is both the honest
regime for the claim being benched (workers OVERLAP waiting, which is
what a pod's input pipeline actually amortizes — storage fetch, not
arithmetic) and the regime the acceptance gate pins: **>= 1.5x img/s
at 4 workers vs 1**.

The bench also counter-asserts the steady-state discipline from the
ISSUE: with enough workers the consumer must see ZERO ``data_stall``
bubbles while a real fit consumes the stream, and the fit must not
recompile past its first batch (``xla_compile_ms`` count stable).

Usage: python tools/perf/data_bench.py [--quick] [--json PATH]
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np

BATCH = 8
FEAT = 64
STALL_S = 0.004           # per-record "storage fetch" latency


def _dataset(tmpdir, n):
    import mxnet_tpu as mx
    rec = os.path.join(tmpdir, "bench.rec")
    idx = os.path.join(tmpdir, "bench.idx")
    rng = np.random.RandomState(0)
    w = mx.recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        w.write_idx(i, mx.recordio.pack(
            mx.recordio.IRHeader(0, float(i % 3), i, 0),
            rng.uniform(-1, 1, FEAT).astype(np.float32).tobytes()))
    w.close()
    return rec, idx


def _loader(rec, idx, workers, stall_s=STALL_S):
    import mxnet_tpu as mx
    transform = mx.data.RawTransform((FEAT,))
    if stall_s:
        transform = mx.data.StallTransform(transform, stall_s)
    return mx.data.DataLoader(
        rec, idx_path=idx, batch_size=BATCH, transform=transform,
        shuffle=True, seed=3, num_workers=workers, queue_depth=8,
        part=(0, 1), label_name="softmax_label")


def bench_scaling(rec, idx, worker_counts, epochs):
    """Pure-iteration img/s per worker count (no model: the loader is
    the system under test)."""
    out = {}
    for workers in worker_counts:
        dl = _loader(rec, idx, workers)
        n = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            for batch in dl:
                n += batch.data[0].shape[0]
            dl.reset()
        dt = time.perf_counter() - t0
        dl.close()
        out[workers] = {"records": n, "wall_s": round(dt, 3),
                        "img_per_s": round(n / dt, 1)}
        print("  %d worker(s): %7.1f img/s  (%d records in %.2fs)"
              % (workers, n / dt, n, dt))
    return out


def bench_steady_state_fit(rec, idx, workers):
    """A real fit over an UNSTALLED stream — the steady state, where
    decode keeps up with the step: assert zero loader stalls, zero
    steady-state recompiles, zero per-batch host syncs, and that the
    batches flowed through the loader's own device-placement stage
    (``data_device_placed`` — the direct device_put path that replaced
    the PrefetchingIter wrapper's extra host copy). (The stalled scaling
    pipeline above is decode-bound by construction; its bubbles are the
    measurement, not a regression.)"""
    import mxnet_tpu as mx
    from mxnet_tpu import profiler

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    sym = mx.sym.SoftmaxOutput(fc2, name="softmax")

    dl = _loader(rec, idx, workers, stall_s=0.0)
    mod = mx.mod.Module(sym, context=mx.cpu())
    stall0 = profiler.get_counter("data_stall")
    mx.random.seed(0)
    # epoch 0 warms the jit cache; loop_recompile already only counts
    # executable-cache growth PAST the warmup compile
    mod.fit(dl, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    compiles0 = profiler.get_counter("loop_recompile")
    syncs0 = profiler.get_counter("loop_host_sync")
    placed0 = profiler.get_counter("data_device_placed")
    t0 = time.perf_counter()
    mod.fit(dl, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    wall = time.perf_counter() - t0
    stalls = profiler.get_counter("data_stall") - stall0
    recompiles = profiler.get_counter("loop_recompile") - compiles0
    host_syncs = profiler.get_counter("loop_host_sync") - syncs0
    placed = profiler.get_counter("data_device_placed") - placed0
    batches = profiler.get_counter("data_batches")
    dl.close()
    return {"workers": workers, "fit_wall_s": round(wall, 3),
            "batches_delivered": batches,
            "steady_state_stalls": stalls,
            "steady_state_recompiles": recompiles,
            "steady_state_host_syncs": host_syncs,
            "device_placed": placed}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import tempfile
    import mxnet_tpu as mx  # noqa: F401 (forces full import before timing)

    n = 64 if args.quick else 192
    epochs = 1 if args.quick else 2
    tmpdir = tempfile.mkdtemp(prefix="mx-data-bench-")
    rec, idx = _dataset(tmpdir, n)

    t_start = time.perf_counter()
    print("scaling (decode-bound: %.0fms/record stall, batch %d):"
          % (STALL_S * 1e3, BATCH))
    scaling = bench_scaling(rec, idx, (1, 2, 4), epochs)
    speedup_4v1 = round(
        scaling[4]["img_per_s"] / scaling[1]["img_per_s"], 2)
    print("  4-worker vs 1-worker speedup: %.2fx (gate: >= 1.5x)"
          % speedup_4v1)

    steady = bench_steady_state_fit(rec, idx, workers=4)
    print("steady-state fit: %d stalls, %d recompiles, %d host syncs, "
          "%d batches device-placed by the loader"
          % (steady["steady_state_stalls"],
             steady["steady_state_recompiles"],
             steady["steady_state_host_syncs"],
             steady["device_placed"]))

    results = {
        "stall_ms_per_record": STALL_S * 1e3,
        "records": n,
        "batch_size": BATCH,
        "scaling": {str(k): v for k, v in scaling.items()},
        "speedup_4workers_vs_1": speedup_4v1,
        "steady_state": steady,
        "note": ("latency-bound pipeline (StallTransform): the CI host "
                 "has 1 cpu core, so worker scaling is demonstrated on "
                 "overlapped IO latency, the regime a pod input "
                 "pipeline actually amortizes"),
    }
    payload = {"bench": "data", "quick": bool(args.quick),
               "elapsed_s": round(time.perf_counter() - t_start, 1),
               "results": results}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=False)
        print("wrote", args.json)

    ok = speedup_4v1 >= 1.5 and steady["steady_state_stalls"] == 0 \
        and steady["steady_state_recompiles"] == 0 \
        and steady["steady_state_host_syncs"] == 0 \
        and steady["device_placed"] > 0
    print("GATE:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

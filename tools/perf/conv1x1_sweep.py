"""Measure 1x1-conv lowering alternatives on the real chip.

ResNet-50's 1x1 convs measured 14 TF/s as `lax.conv_general_dilated`
(docs/perf.md conv table) while plain matmuls sustain 154-170 TF/s on
this chip. A 1x1 stride-1 conv IS a matmul over N*H*W rows; this sweep
times the conv lowering against an explicit transpose+reshape+dot
lowering, fwd+bwd, bf16, batch 256 (flops counted 3x forward).

Timing discipline (docs/perf.md preamble): in-program lax.scan
amortization, scalar-read fencing, operands passed as jit args.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

ITERS = 30

# (H, Cin, Cout) at batch 256, stride 1 — every distinct 1x1 shape in
# ResNet-50 v2 (both directions of each bottleneck + shortcuts)
SHAPES = [
    (56, 64, 64), (56, 64, 256), (56, 256, 64), (56, 256, 128),
    (28, 128, 512), (28, 512, 256), (28, 256, 1024),
    (14, 256, 1024), (14, 1024, 512), (14, 512, 2048),
    (7, 512, 2048), (7, 2048, 512),
]
N = 256

def conv_fn(x, w):
    # framework convention (amp.mxu_operands): bf16 convs rely on the
    # MXU's native fp32 accumulation; no explicit accumulation request
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))

def dot_fn(x, w):
    n, c, h, _w = x.shape
    k = w.shape[0]
    xm = x.transpose(0, 2, 3, 1).reshape(n * h * _w, c)
    wm = w.reshape(k, c).T
    y = jnp.dot(xm, wm, preferred_element_type=jnp.float32)
    y = y.astype(jnp.bfloat16)
    return y.reshape(n, h, _w, k).transpose(0, 3, 1, 2)

def timed(fn, x, w):
    def loss(x, w):
        return jnp.sum(fn(x, w).astype(jnp.float32))
    g = jax.grad(loss, argnums=(0, 1))

    def body(carry, _):
        x, w = carry
        gx, gw = g(x, w)
        return (x + 1e-6 * gx.astype(x.dtype),
                w + 1e-6 * gw.astype(w.dtype)), ()

    @jax.jit
    def run(x, w):
        (x, w), _ = lax.scan(body, (x, w), None, length=ITERS)
        return x[0, 0, 0, 0].astype(jnp.float32)

    r = run(x, w); r.block_until_ready(); float(r)  # compile + warm
    t0 = time.perf_counter()
    r = run(x, w); float(r)
    dt = (time.perf_counter() - t0) / ITERS
    return dt

def main():
    print("H  Cin->Cout   conv TF/s   dot TF/s   speedup")
    rows = []
    for H, ci, co in SHAPES:
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (N, ci, H, H), jnp.bfloat16)
        w = jax.random.normal(key, (co, ci, 1, 1), jnp.bfloat16) * 0.05
        fl = 3 * 2.0 * N * H * H * ci * co
        tc = timed(conv_fn, x, w)
        td = timed(dot_fn, x, w)
        rows.append((H, ci, co, fl / tc / 1e12, fl / td / 1e12))
        print("%3d %5d->%-5d %8.1f %10.1f %8.2fx"
              % (H, ci, co, fl / tc / 1e12, fl / td / 1e12, tc / td))
    tot_c = sum(2 * N * h * h * a * b / (r1 * 1e12)
                for (h, a, b, r1, _) in rows)
    tot_d = sum(2 * N * h * h * a * b / (r2 * 1e12)
                for (h, a, b, _, r2) in rows)
    print("aggregate 1x1 time: conv %.1f ms  dot %.1f ms  (%.2fx)"
          % (tot_c * 1e3 * 3, tot_d * 1e3 * 3, tot_c / tot_d))

if __name__ == "__main__":
    main()

"""Per-op profile of the ResNet-50 fused train step on the real chip.

Aggregates the XLA trace by op category to show where the 110 ms goes:
conv MXU work vs BN/elementwise HBM traffic vs overhead.
"""
import sys, json, gzip, glob, os, collections
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu.models import resnet

B = 256
mx.amp.init("bfloat16")
sym = resnet.get_symbol(num_classes=1000, num_layers=50)
mod = mx.mod.Module(sym, context=mx.tpu(0))
mod.bind(data_shapes=[("data", (B, 3, 224, 224))],
         label_shapes=[("softmax_label", (B,))])
mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                               magnitude=2))
mod.init_optimizer(optimizer="sgd", optimizer_params={
    "learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4})
rng = np.random.RandomState(0)
db = mx.io.DataBatch(
    data=[mx.nd.array(rng.uniform(-1, 1, (B, 3, 224, 224)).astype(np.float32))],
    label=[mx.nd.array(rng.randint(0, 1000, (B,)).astype(np.float32))])

def drain():
    return float(np.asarray(mod._exec.arg_dict["fc1_weight"].data[0, 0]))

for _ in range(3):
    mod._fit_step(db)
drain()

logdir = "/tmp/resnet_prof"
os.system("rm -rf " + logdir)
with jax.profiler.trace(logdir):
    for _ in range(6):
        mod._fit_step(db)
    drain()

# aggregate by tf_op from the trace-event json
files = glob.glob(logdir + "/**/*.trace.json.gz", recursive=True)
assert files, os.popen("find %s -type f" % logdir).read()
ev = json.load(gzip.open(files[0]))["traceEvents"]
agg = collections.defaultdict(lambda: [0.0, 0.0, 0])  # dur_ms, bytes, n
total = 0.0
for e in ev:
    if e.get("ph") != "X" or "args" not in e:
        continue
    a = e["args"]
    if "device_duration_ps" not in a and "tf_op" not in a:
        continue
    dur = float(a.get("device_duration_ps", e.get("dur", 0) * 1e6)) / 1e9  # ms
    op = a.get("tf_op", e.get("name", "?"))
    # collapse to coarse category
    name = e.get("name", "")
    key = op.split("/")[-1] if "/" in op else op
    agg[key][0] += dur
    agg[key][1] += float(a.get("bytes_accessed", 0))
    agg[key][2] += 1
    total += dur
rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
print("%-46s %9s %8s %6s %9s" % ("op", "ms/step", "%", "n", "GB/s"))
for k, (d, by, n) in rows[:40]:
    d6 = d / 6
    bw = by / 6 / (d6 / 1e3) / 1e9 if d6 > 0 else 0
    print("%-46s %9.3f %7.1f%% %6d %9.0f" % (k[:46], d6, 100 * d / total,
                                             n // 6, bw))
print("TOTAL device time: %.1f ms/step over 6 steps" % (total / 6))

"""Per-op profile of the ResNet-50 fused train step on the real chip.

Aggregates the XLA trace by op category to show where the 110 ms goes:
conv MXU work vs BN/elementwise HBM traffic vs overhead.
"""
import sys, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu.models import resnet

B = 256
mx.amp.init("bfloat16")
sym = resnet.get_symbol(num_classes=1000, num_layers=50)
mod = mx.mod.Module(sym, context=mx.tpu(0))
mod.bind(data_shapes=[("data", (B, 3, 224, 224))],
         label_shapes=[("softmax_label", (B,))])
mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                               magnitude=2))
mod.init_optimizer(optimizer="sgd", optimizer_params={
    "learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4})
rng = np.random.RandomState(0)
db = mx.io.DataBatch(
    data=[mx.nd.array(rng.uniform(-1, 1, (B, 3, 224, 224)).astype(np.float32))],
    label=[mx.nd.array(rng.randint(0, 1000, (B,)).astype(np.float32))])

def drain():
    return float(np.asarray(mod._exec.arg_dict["fc1_weight"].data[0, 0]))

for _ in range(3):
    mod._fit_step(db)
drain()

logdir = "/tmp/resnet_prof"
os.system("rm -rf " + logdir)
with jax.profiler.trace(logdir):
    for _ in range(6):
        mod._fit_step(db)
    drain()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _trace import aggregate_trace, print_rows

print_rows(aggregate_trace(logdir, 6), limit=40)

"""Round-5 ResNet restructuring sweep (VERDICT r4 item 1).

Three candidate transformations vs the framework's NCHW conv lowering,
measured fwd+bwd bf16 on the real chip (flops 3x forward):

  a) NHWC 1x1 conv as a pure reshape+dot (no transposes at all)
  b) NHWC conv lowering (for the 3x3s that would have to switch layout
     alongside the 1x1s)
  c) space-to-depth stem: 7x7/2 pad 3 on (N,3,224,224) rewritten as a
     mathematically identical 4x4/1 valid conv on the 2x2
     space-to-depth input (Cin 3->12, contraction 147->192)

Timing discipline: lax.scan amortization, scalar-read fence, operands
as jit args (docs/perf.md preamble).
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

ITERS = 30
N = 256

def timed(fn, *ops):
    def loss(*ops):
        return jnp.sum(fn(*ops).astype(jnp.float32))
    g = jax.grad(loss, argnums=tuple(range(len(ops))))

    def body(carry, _):
        gs = g(*carry)
        return tuple(o + 1e-6 * gg.astype(o.dtype)
                     for o, gg in zip(carry, gs)), ()

    @jax.jit
    def run(*ops):
        out, _ = lax.scan(body, ops, None, length=ITERS)
        return out[0].ravel()[0].astype(jnp.float32)

    r = run(*ops); r.block_until_ready(); float(r)
    t0 = time.perf_counter()
    float(run(*ops))
    return (time.perf_counter() - t0) / ITERS

def conv(dn):
    def f(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
            dimension_numbers=dn)
    return f

def dot_nhwc(x, w):   # x (N,H,W,C), w (C,K)
    n, h, ww, c = x.shape
    y = jnp.dot(x.reshape(n * h * ww, c), w)
    return y.reshape(n, h, ww, -1)

SHAPES = [
    (56, 64, 64), (56, 64, 256), (56, 256, 64), (56, 256, 128),
    (28, 128, 512), (28, 512, 256), (14, 256, 1024), (14, 1024, 512),
    (7, 512, 2048), (7, 2048, 512),
]

def main():
    key = jax.random.PRNGKey(0)
    print("== 1x1 shapes: NCHW conv vs NHWC conv vs NHWC dot (TF/s) ==")
    agg = [0.0, 0.0, 0.0]
    for H, ci, co in SHAPES:
        x1 = jax.random.normal(key, (N, ci, H, H), jnp.bfloat16)
        w1 = jax.random.normal(key, (co, ci, 1, 1), jnp.bfloat16) * .05
        x2 = jnp.transpose(x1, (0, 2, 3, 1))
        w2 = jnp.transpose(w1, (2, 3, 1, 0))  # HWIO
        wd = w1.reshape(co, ci).T
        fl = 3 * 2.0 * N * H * H * ci * co
        t = [timed(conv(("NCHW", "OIHW", "NCHW")), x1, w1),
             timed(conv(("NHWC", "HWIO", "NHWC")), x2, w2),
             timed(dot_nhwc, x2, wd)]
        for i in range(3):
            agg[i] += t[i]
        print("%3d %5d->%-5d %8.1f %8.1f %8.1f" %
              ((H, ci, co) + tuple(fl / tt / 1e12 for tt in t)))
    print("aggregate 1x1 ms: NCHW-conv %.1f  NHWC-conv %.1f  NHWC-dot %.1f"
          % tuple(1e3 * a for a in agg))

    print("== 3x3 shapes: NCHW conv vs NHWC conv (TF/s) ==")
    for H, c, s in [(56, 64, 1), (28, 128, 1), (14, 256, 1), (7, 512, 1),
                    (56, 128, 2), (28, 256, 2), (14, 512, 2)]:
        x1 = jax.random.normal(key, (N, c, H, H), jnp.bfloat16)
        w1 = jax.random.normal(key, (c * (2 if s > 1 else 1), c, 3, 3),
                               jnp.bfloat16) * .05
        x2 = jnp.transpose(x1, (0, 2, 3, 1))
        w2 = jnp.transpose(w1, (2, 3, 1, 0))
        co = w1.shape[0]
        Ho = H // s
        fl = 3 * 2.0 * N * Ho * Ho * c * co * 9

        def c1(x, w):
            return lax.conv_general_dilated(
                x, w, window_strides=(s, s), padding=[(1, 1), (1, 1)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))

        def c2(x, w):
            return lax.conv_general_dilated(
                x, w, window_strides=(s, s), padding=[(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        t1, t2 = timed(c1, x1, w1), timed(c2, x2, w2)
        print("%3d c%4d s%d  %8.1f %8.1f" %
              (H, c, s, fl / t1 / 1e12, fl / t2 / 1e12))

    print("== stem: 7x7/2 direct vs space-to-depth(2) ==")
    x = jax.random.normal(key, (N, 3, 224, 224), jnp.bfloat16)
    w = jax.random.normal(key, (64, 3, 7, 7), jnp.bfloat16) * .05
    fl = 3 * 2.0 * N * 112 * 112 * 64 * 3 * 49

    def stem_direct(x, w):
        return lax.conv_general_dilated(
            x, w, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def s2d(x):  # (N,C,H,W) -> (N,C*4,H/2,W/2)
        n, c, h, ww = x.shape
        x = x.reshape(n, c, h // 2, 2, ww // 2, 2)
        return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, h // 2,
                                                     ww // 2)

    def wt(w):  # (K,C,7,7) -> padded (K,C,8,8) -> (K,C*4,4,4)
        k, c = w.shape[:2]
        wp = jnp.pad(w, ((0, 0), (0, 0), (0, 1), (0, 1)))
        wp = wp.reshape(k, c, 4, 2, 4, 2)
        return wp.transpose(0, 1, 3, 5, 2, 4).reshape(k, c * 4, 4, 4)

    def stem_s2d(x, w):
        xp = jnp.pad(x, ((0, 0), (0, 0), (3, 3), (3, 3)))
        xs = s2d(xp)            # (N,12,115,115)
        return lax.conv_general_dilated(
            xs, wt(w), window_strides=(1, 1), padding=[(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    # numeric equivalence check first (fp32, CPU-precision caveats ok on TPU)
    xf = jax.random.normal(key, (2, 3, 32, 32), jnp.float32)
    wf = jax.random.normal(key, (4, 3, 7, 7), jnp.float32)
    a = jax.jit(stem_direct)(xf, wf)
    b = jax.jit(stem_s2d)(xf, wf)
    err = float(jnp.max(jnp.abs(a - b)))
    print("s2d equivalence max err:", err)
    assert err < 1e-3, err
    t1, t2 = timed(stem_direct, x, w), timed(stem_s2d, x, w)
    print("stem direct %.1f TF/s (%.2f ms)   s2d %.1f TF/s (%.2f ms)"
          % (fl / t1 / 1e12, t1 * 1e3, fl / t2 / 1e12, t2 * 1e3))

if __name__ == "__main__":
    main()

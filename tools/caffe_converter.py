"""Convert a Caffe deploy prototxt (+ optional weights) to a framework
checkpoint (symbol JSON + .params).

Capability twin of the reference's ``tools/caffe_converter`` for the
common deployment subset — without needing caffe or protobuf installed:
the prototxt text format is parsed directly, and weights arrive as an
``.npz`` (``{layer_name}_weight`` / ``{layer_name}_bias`` arrays, the
shape caffe stores: conv OIHW, inner-product (out, in) — both match this
framework's layouts, so no transposes are needed). BatchNorm+Scale pairs
use the symbol's own names instead: ``{bn_name}_gamma``/``{bn_name}_beta``
(from the Scale layer's blobs) and ``{bn_name}_moving_mean``/
``{bn_name}_moving_var`` (the BatchNorm layer's mean/variance blobs,
divided by its scale factor blob).

Supported layer types: Input/Data, Convolution, InnerProduct, Pooling
(MAX/AVE, incl. global), ReLU, Sigmoid, TanH, LRN, Dropout, Softmax,
SoftmaxWithLoss, Concat, Eltwise (SUM/MAX/PROD), Flatten, BatchNorm (+
the following Scale layer folded in).

Binary ``.caffemodel`` weights are read directly with a minimal
protobuf wire-format (varint) reader — no protobuf library: the
NetParameter message is scanned for ``layer`` (field 100,
LayerParameter: name=1, blobs=7) and legacy ``layers`` (field 2,
V1LayerParameter: name=4, blobs=6) entries; each BlobProto carries its
shape either as BlobShape dims (field 7) or legacy
num/channels/height/width (fields 1-4) and float data packed or
unpacked in field 5 (doubles in field 8). This mirrors the reference's
``tools/caffe_converter/convert_model.py``, which used compiled
protobuf classes for the same traversal.

  python tools/caffe_converter.py deploy.prototxt out_prefix \
      [--weights weights.npz | --caffemodel net.caffemodel]

Writes ``out_prefix-symbol.json`` (+ ``out_prefix-0000.params`` when
weights are given) — loadable by ``mx.mod.Module`` / ``mx.predictor``.
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------- prototxt parsing


def _tokenize(text):
    text = re.sub(r"#[^\n]*", "", text)
    return re.findall(r"[{}]|[A-Za-z_][\w.]*\s*:|\"[^\"]*\"|[^\s{}]+", text)


def parse_prototxt(text):
    """Parse protobuf text format into nested dicts; repeated fields
    become lists."""
    toks = _tokenize(text)
    pos = [0]

    def value(tok):
        if tok.startswith('"'):
            return tok[1:-1]
        try:
            return int(tok)
        except ValueError:
            pass
        try:
            return float(tok)
        except ValueError:
            pass
        if tok in ("true", "false"):
            return tok == "true"
        return tok                       # enum keyword (MAX, SUM, ...)

    def block():
        out = {}
        while pos[0] < len(toks):
            tok = toks[pos[0]]
            if tok == "}":
                pos[0] += 1
                return out
            if tok.endswith(":"):
                key = tok[:-1].strip()
                pos[0] += 1
                if toks[pos[0]] == "{":   # 'field: { ... }' message form
                    pos[0] += 1
                    v = block()
                else:
                    v = value(toks[pos[0]])
                    pos[0] += 1
            else:
                key = tok
                pos[0] += 1
                if toks[pos[0]] == "{":
                    pos[0] += 1
                v = block()
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(v)
            else:
                out[key] = v
        return out

    return block()


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _pair(param, base, default):
    """kernel_size/stride/pad with optional _h/_w variants."""
    if param.get(base + "_h") is not None:
        return (int(param[base + "_h"]), int(param[base + "_w"]))
    v = param.get(base)
    if v is None:
        return (default, default)
    if isinstance(v, list):
        v = v[0]
    return (int(v), int(v))


# ------------------------------------------------------- layer conversion


def convert(net_def, input_shape=None):
    """Build an mx Symbol from a parsed deploy net. Returns (symbol,
    input_shape)."""
    import mxnet_tpu as mx

    layers = _as_list(net_def.get("layer")) or _as_list(net_def.get("layers"))
    tops = {}
    in_shape = input_shape

    if "input" in net_def:          # classic "input:/input_dim:" header
        name = net_def["input"]
        name = name[0] if isinstance(name, list) else name
        tops[name] = mx.sym.Variable("data")
        dims = [int(d) for d in _as_list(net_def.get("input_dim"))]
        if not dims and "input_shape" in net_def:
            dims = [int(d) for d in
                    _as_list(net_def["input_shape"].get("dim"))]
        if dims:
            in_shape = tuple(dims)

    def bottom(l):
        bots = _as_list(l.get("bottom"))
        return [tops[b] for b in bots]

    for l in layers:
        ltype = str(l.get("type"))
        name = str(l.get("name"))
        top_names = _as_list(l.get("top")) or [name]
        if ltype in ("Input", "Data"):
            tops[top_names[0]] = mx.sym.Variable("data")
            shp = l.get("input_param", {}).get("shape", {})
            dims = [int(d) for d in _as_list(shp.get("dim"))]
            if dims:
                in_shape = tuple(dims)
            continue
        bots = bottom(l)
        x = bots[0] if bots else None
        if ltype == "Convolution":
            p = l.get("convolution_param", {})
            out = mx.sym.Convolution(
                x, num_filter=int(p["num_output"]),
                kernel=_pair(p, "kernel_size", 1),
                stride=_pair(p, "stride", 1), pad=_pair(p, "pad", 0),
                num_group=int(p.get("group", 1)),
                no_bias=not p.get("bias_term", True), name=name)
        elif ltype == "InnerProduct":
            p = l.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                x, num_hidden=int(p["num_output"]),
                no_bias=not p.get("bias_term", True), name=name)
        elif ltype == "Pooling":
            p = l.get("pooling_param", {})
            ptype = "avg" if str(p.get("pool", "MAX")) == "AVE" else "max"
            if p.get("global_pooling"):
                out = mx.sym.Pooling(x, global_pool=True, pool_type=ptype,
                                     kernel=(1, 1), name=name)
            else:
                out = mx.sym.Pooling(
                    x, kernel=_pair(p, "kernel_size", 1),
                    stride=_pair(p, "stride", 1), pad=_pair(p, "pad", 0),
                    pool_type=ptype,
                    pooling_convention="full",   # caffe ceil-mode windows
                    name=name)
        elif ltype == "ReLU":
            out = mx.sym.Activation(x, act_type="relu", name=name)
        elif ltype == "Sigmoid":
            out = mx.sym.Activation(x, act_type="sigmoid", name=name)
        elif ltype == "TanH":
            out = mx.sym.Activation(x, act_type="tanh", name=name)
        elif ltype == "LRN":
            p = l.get("lrn_param", {})
            out = mx.sym.LRN(x, nsize=int(p.get("local_size", 5)),
                             alpha=float(p.get("alpha", 1.0)),
                             beta=float(p.get("beta", 0.75)),
                             knorm=float(p.get("k", 1.0)), name=name)
        elif ltype == "Dropout":
            p = l.get("dropout_param", {})
            out = mx.sym.Dropout(x, p=float(p.get("dropout_ratio", 0.5)),
                                 name=name)
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            out = mx.sym.SoftmaxOutput(x, name="softmax")
        elif ltype == "Concat":
            out = mx.sym.Concat(*bots, name=name)
        elif ltype == "Eltwise":
            p = l.get("eltwise_param", {})
            op = str(p.get("operation", "SUM"))
            out = bots[0]
            for b in bots[1:]:
                out = (out + b if op == "SUM" else
                       out * b if op == "PROD" else
                       mx.sym.maximum(out, b))
        elif ltype == "Flatten":
            out = mx.sym.Flatten(x, name=name)
        elif ltype == "BatchNorm":
            p = l.get("batch_norm_param", {})
            out = mx.sym.BatchNorm(x, eps=float(p.get("eps", 1e-5)),
                                   use_global_stats=True, fix_gamma=False,
                                   name=name)
        elif ltype == "Scale":
            # caffe pairs BatchNorm with a Scale layer; the BatchNorm
            # symbol above already carries gamma/beta, so Scale is an
            # alias of its bottom
            out = x
        else:
            raise NotImplementedError(
                "caffe layer type %r (layer %r) is not supported by this "
                "converter" % (ltype, name))
        tops[top_names[0]] = out

    last = tops[_as_list(layers[-1].get("top"))[0]
                if layers[-1].get("top") else str(layers[-1]["name"])]
    return last, in_shape


# ------------------------------------------------- caffemodel wire reader


def _read_varint(buf, i):
    shift, out = 0, 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _iter_fields(buf, start=0, end=None):
    """Yield (field_number, wire_type, payload) over a protobuf message.
    wire types: 0 varint (payload int), 1 64-bit, 2 length-delimited,
    5 32-bit (payload bytes)."""
    i, end = start, len(buf) if end is None else end
    while i < end:
        key, i = _read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError("unsupported protobuf wire type %d" % wt)
        yield fno, wt, v


def _parse_blob(buf):
    """BlobProto -> numpy array with its declared shape."""
    import numpy as np
    legacy = {}
    dims = None
    chunks = []
    for fno, wt, v in _iter_fields(buf):
        if fno in (1, 2, 3, 4) and wt == 0:
            legacy[fno] = v
        elif fno == 5:      # repeated float data (packed or not)
            chunks.append(np.frombuffer(v, "<f4"))
        elif fno == 8:      # repeated double data
            chunks.append(np.frombuffer(v, "<f8").astype(np.float32))
        elif fno == 7 and wt == 2:      # BlobShape
            dims = []
            for f2, w2, v2 in _iter_fields(v):
                if f2 != 1:
                    continue
                if w2 == 0:             # unpacked dim
                    dims.append(v2)
                else:                   # packed varints
                    j = 0
                    while j < len(v2):
                        d, j = _read_varint(v2, j)
                        dims.append(d)
    data = np.concatenate(chunks) if chunks else np.zeros(0, np.float32)
    if dims is None and legacy:
        # keep legacy 4-D dims verbatim: stripping leading ones here
        # would corrupt e.g. a num_output=1 conv weight (1, C, kh, kw);
        # consumers that want flat views (InnerProduct, biases, BN
        # stats) reshape/ravel in caffemodel_weights
        dims = [legacy.get(k, 1) for k in (1, 2, 3, 4)]
    if dims:
        data = data.reshape([int(d) for d in dims])
    return data


def parse_caffemodel(raw):
    """Parse NetParameter bytes -> ordered list of
    ``(layer_name, [blob arrays])`` (new-style ``layer`` field 100 and
    legacy ``layers`` field 2 both supported)."""
    out = []
    for fno, wt, v in _iter_fields(raw):
        if wt != 2 or fno not in (2, 100):
            continue
        name_field, blob_field = (4, 6) if fno == 2 else (1, 7)
        name, blobs = None, []
        for f2, w2, v2 in _iter_fields(v):
            if f2 == name_field and w2 == 2:
                name = v2.decode("utf-8", "replace")
            elif f2 == blob_field and w2 == 2:
                blobs.append(_parse_blob(v2))
        if name is not None and blobs:
            out.append((name, blobs))
    return out


def caffemodel_weights(net_def, raw):
    """Map a parsed ``.caffemodel`` onto this converter's parameter
    names (the ``--weights`` npz convention): conv/IP blobs ->
    ``{name}_weight``/``{name}_bias``; BatchNorm mean/var (divided by
    the scale-factor blob) -> ``{name}_moving_mean``/``_moving_var``;
    a following Scale layer's blobs -> the BatchNorm's
    ``{bn}_gamma``/``{bn}_beta``."""
    import numpy as np
    blobs = dict(parse_caffemodel(raw))
    layers = _as_list(net_def.get("layer")) or _as_list(net_def.get("layers"))
    by_name = {str(l["name"]): l for l in layers}
    # Scale layers fold into the BatchNorm they follow (matched by
    # bottom, like convert() does)
    bn_of_top = {}
    for l in layers:
        if str(l.get("type")) == "BatchNorm":
            for top in _as_list(l.get("top", [])):
                bn_of_top[str(top)] = str(l["name"])
    out = {}
    for name, layer_blobs in blobs.items():
        ldef = by_name.get(name, {})
        ltype = str(ldef.get("type", ""))
        if ltype == "BatchNorm" or (not ldef and len(layer_blobs) == 3
                                    and layer_blobs[2].size == 1):
            mean, var = layer_blobs[0], layer_blobs[1]
            if len(layer_blobs) > 2 and layer_blobs[2].size == 1:
                sf = float(layer_blobs[2].ravel()[0])
                if sf != 0:
                    mean, var = mean / sf, var / sf
            out[name + "_moving_mean"] = mean.ravel()
            out[name + "_moving_var"] = var.ravel()
        elif ltype == "Scale":
            bn = bn_of_top.get(str(_as_list(ldef.get("bottom", []))[0]),
                               name)
            out[bn + "_gamma"] = layer_blobs[0].ravel()
            if len(layer_blobs) > 1:
                out[bn + "_beta"] = layer_blobs[1].ravel()
        else:
            w = layer_blobs[0]
            if ltype == "InnerProduct" and w.ndim > 2:
                w = w.reshape(w.shape[-2], w.shape[-1])
            out[name + "_weight"] = w
            if len(layer_blobs) > 1:
                out[name + "_bias"] = layer_blobs[1].ravel()
    return out


def main():
    ap = argparse.ArgumentParser(description="caffe prototxt -> mx symbol")
    ap.add_argument("prototxt")
    ap.add_argument("out_prefix")
    wsrc = ap.add_mutually_exclusive_group()
    wsrc.add_argument("--weights", default=None,
                      help=".npz with {layer}_weight/{layer}_bias arrays")
    wsrc.add_argument("--caffemodel", default=None,
                      help="binary .caffemodel to read weights from "
                           "(varint-level protobuf reader, no caffe/"
                           "protobuf needed)")
    args = ap.parse_args()

    import numpy as np
    import mxnet_tpu as mx

    with open(args.prototxt) as f:
        net_def = parse_prototxt(f.read())
    sym, in_shape = convert(net_def)
    sym.save(args.out_prefix + "-symbol.json")
    print("wrote %s-symbol.json (input shape %s)"
          % (args.out_prefix, in_shape))

    arrays = None
    if args.weights:
        with np.load(args.weights) as z:
            arrays = {k: z[k] for k in z.files}
    elif args.caffemodel:
        with open(args.caffemodel, "rb") as f:
            arrays = caffemodel_weights(net_def, f.read())
        print("parsed %d parameter tensors from %s"
              % (len(arrays), args.caffemodel))

    if arrays is not None:
        blob, skipped = {}, []
        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        for k, v in arrays.items():
            if k in arg_names:
                blob["arg:" + k] = mx.nd.array(v)
            elif k in aux_names:
                blob["aux:" + k] = mx.nd.array(v)
            else:
                skipped.append(k)
        if skipped:
            print("  skipped %d arrays with no matching symbol arg: %s"
                  % (len(skipped), skipped[:6]))
            print("  (expected names: %s ...)"
                  % sorted(arg_names | aux_names)[:6])
        if not blob:
            ap.error("none of the npz arrays matched the symbol's "
                     "parameters — check the naming convention in the "
                     "module docstring")
        mx.nd.save(args.out_prefix + "-0000.params", blob, format="mxnet")
        print("wrote %s-0000.params (%d tensors)"
              % (args.out_prefix, len(blob)))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""End-to-end crash-safety smoke for mx.checkpoint (CI `checkpoint` step,
also driven by tests/test_checkpoint.py::test_kill9_resume_smoke_script).

The honest failure drill, in one script:

1. a child process trains with async checkpointing and is SIGKILLed
   DURING a checkpoint write (deterministically, via the
   ``MXNET_TPU_CKPT_TEST_CRASH=<point>@<n>`` fault hook — the N-th write
   dies mid-``arrays.npz``);
2. the parent verifies the torn write left only a ``.tmp-*`` residue and
   earlier checkpoints verify clean;
3. the parent then byte-flips the NEWEST surviving checkpoint (bit-rot),
   so resume must detect the corruption and fall back another step;
4. ``fit(resume_from=...)`` completes the run from the oldest surviving
   checkpoint and must reproduce an uninterrupted run's params
   BIT-IDENTICALLY.

Exit 0 + ``KILL-RESUME-PARITY-OK`` on success; any assertion kills CI.
"""
import os
import signal
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, NCLS = 8, 64, 16, 8
EPOCHS = 4


def _symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=12, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=NCLS, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _data():
    rng = np.random.RandomState(0)
    return (rng.uniform(-1, 1, (NSAMP, FEAT)).astype(np.float32),
            rng.randint(0, NCLS, (NSAMP,)).astype(np.float32))


def _train(epochs, ckpt_dir=None, resume=None, seed=True):
    import mxnet_tpu as mx
    mx.random.seed(7)
    sym = _symbol()
    X, Y = _data()
    kw = {}
    if seed:
        rng = np.random.RandomState(42)
        args, _, _ = sym.infer_shape(data=(BATCH, FEAT),
                                     softmax_label=(BATCH,))
        kw["arg_params"] = {
            n: mx.nd.array(rng.uniform(-0.1, 0.1, s).astype(np.float32))
            for n, s in zip(sym.list_arguments(), args)
            if n not in ("data", "softmax_label")}
    ckpt = None
    if ckpt_dir is not None:
        ckpt = mx.checkpoint.CheckpointConfig(
            ckpt_dir, every_n_batches=3, period_epochs=1, keep_last=0)
    it = mx.io.NDArrayIter(X, Y, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            checkpoint=ckpt, resume_from=resume, **kw)
    arg, _aux = mod.get_params()
    return {k: v.asnumpy().copy() for k, v in arg.items()}


def main():
    if "--child" in sys.argv:
        _train(EPOCHS, ckpt_dir=sys.argv[sys.argv.index("--child") + 1])
        print("CHILD-FINISHED-WITHOUT-CRASH")       # must not be reached
        return 1

    import mxnet_tpu as mx
    base = tempfile.mkdtemp(prefix="ckpt_smoke_")

    # ---- 1. child dies mid-write of its 3rd checkpoint ------------------
    env = {**os.environ, "PYTHONPATH": "",
           "MXNET_TPU_CKPT_TEST_CRASH": "after_arrays@3"}
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", base],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == -signal.SIGKILL, \
        "child should die by SIGKILL, got rc=%s\n%s%s" % (
            proc.returncode, proc.stdout, proc.stderr)
    assert "CHILD-FINISHED-WITHOUT-CRASH" not in proc.stdout

    # ---- 2. torn write left residue only; survivors verify -------------
    entries = mx.checkpoint.list_checkpoints(base)
    steps = [s for s, _ in entries]
    assert len(steps) >= 2, "expected >=2 surviving checkpoints, got %s" \
        % steps
    residue = [n for n in os.listdir(base) if n.startswith(".tmp-")]
    assert residue, "SIGKILL mid-write should leave a .tmp-* residue"
    for _s, p in entries:
        mx.checkpoint.read_checkpoint(p)            # full checksum pass
    print("survivors verify clean: steps=%s residue=%s" % (steps, residue))

    # ---- 3. bit-rot the newest survivor: resume must fall back ---------
    newest = entries[-1][1]
    arrays = os.path.join(newest, "arrays.npz")
    blob = bytearray(open(arrays, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(arrays, "wb").write(bytes(blob))

    # ---- 4. exact-resume parity ----------------------------------------
    w_ref = _train(EPOCHS)
    w_res = _train(EPOCHS, resume=base, seed=False)
    assert set(w_ref) == set(w_res)
    for k in sorted(w_ref):
        np.testing.assert_array_equal(w_ref[k], w_res[k], err_msg=k)

    from mxnet_tpu import profiler
    assert profiler.get_counter("ckpt_load_fallback") >= 1, \
        "resume should have skipped the corrupted newest checkpoint"
    print("KILL-RESUME-PARITY-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

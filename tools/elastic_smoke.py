"""Elastic kill -> reshard -> resume drill (CI ``elastic`` job, also
driven by tests/test_elastic.py::test_elastic_smoke_script).

The full ROADMAP-item-4 story in one script:

1. an 8-device data-parallel ``fit`` is SIGTERMed mid-epoch
   (deterministically: ``MXNET_TPU_FAULTS=fit.batch@K:sigterm``) — the
   preemption hook lands a final synchronous checkpoint and exits 143;
2. the ``mxnet_tpu.elastic`` supervisor observes the preemption,
   re-probes the world, and relaunches the child at 4 devices; the
   child resumes from the newest valid checkpoint, resharding every
   array onto the smaller mesh (reshard-on-load);
3. a second injected preemption drops the world to 2 devices; the
   third attempt finishes the run;
4. the final parameters must be BIT-IDENTICAL to an uninterrupted
   8-device baseline, with ZERO steady-state recompiles after each
   re-entry (``loop_recompile`` asserted at every batch of every
   attempt) and both restarts/reshards visible in the supervisor
   counters;
5. a knobs-off zero-cost gate: the same child with no ``MXNET_TPU_FAULTS``
   must run fault-silent (``fault_injected`` == 0, harness disarmed).

Why the model is a one-hot "lookup regression" (FullyConnected over
one-hot rows + LinearRegressionOutput, no bias): bit-identical params
across DIFFERENT mesh sizes requires every floating-point reduction to
be exact regardless of summation order — with disjoint one-hot inputs
each gradient element receives exactly ONE nonzero contribution, so the
batch contraction and the cross-device psum are order-independent. The
drill therefore isolates elastic/reshard/resume correctness from FP
reduction-order noise (which a change of world size legitimately
perturbs on real models).

Exit 0 + ``ELASTIC-DRILL-OK`` on success; any assertion kills CI.

The MULTI-HOST extension of this drill is ``tools/pod_smoke.py``
(ISSUE 11, CI ``multihost`` job): the same exact one-hot model,
stride-masked per host, driven through a 2-host coordinated pod that
survives ``host.die`` (hostkill and silent-wedge) with bit-identical
parity against an uninterrupted baseline.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

BATCH, NSAMP, FEAT, OUT = 8, 64, 64, 4
EPOCHS = 3
SEED = 5
# faults per supervisor attempt: two preemptions, then run to completion
ATTEMPT_FAULTS = {0: "fit.batch@12:sigterm", 1: "fit.batch@6:sigterm"}
WORLD_SCHEDULE = [8, 4, 2]

# --fsdp mode (ISSUE 14 acceptance drill): the same kill/reshard/resume
# sequence with the unified SpecLayout — params + optimizer states
# sharded over the fsdp axis at every world size; the checkpoint
# reshards 8 -> 4 -> 2 through the SAME layout funnel the bind uses.
# Env-carried so the supervisor's children inherit the mode.
FSDP_ENV = "MXNET_TPU_SMOKE_FSDP"
FSDP_WORLDS = {8: (2, 4), 4: (2, 2), 2: (1, 2), 1: None}


def _fsdp_layout(ndev):
    """dp x fsdp SpecLayout for this world size (None = plain dp).
    min_shard_bytes=0: the drill's lut weight is tiny — the point is
    the sharding machinery, not the HBM savings."""
    shape = FSDP_WORLDS.get(ndev)
    if shape is None:
        return None
    from mxnet_tpu.parallel import SpecLayout
    return SpecLayout(data=shape[0], fsdp=shape[1], min_shard_bytes=0)


def _data():
    """One-hot lookup samples: row i is e_{i mod FEAT}; every batch of 8
    holds disjoint positions (the iterator does not shuffle), so every
    gradient element has exactly one nonzero contributor — see module
    docstring."""
    x = np.eye(FEAT, dtype=np.float32)[np.arange(NSAMP) % FEAT]
    rng = np.random.RandomState(3)
    y = rng.uniform(-1, 1, (NSAMP, OUT)).astype(np.float32)
    return x, y


def _symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=OUT, no_bias=True,
                               name="lut")
    return mx.sym.LinearRegressionOutput(fc, mx.sym.Variable("label"),
                                         name="reg")


def _train(ckpt_dir=None, out_path=None, check_recompiles=False):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import elastic, profiler
    mx.random.seed(SEED)
    ndev = len(jax.devices())
    X, Y = _data()
    it = mx.io.NDArrayIter({"data": X}, {"label": Y}, batch_size=BATCH)
    layout = _fsdp_layout(ndev) if os.environ.get(FSDP_ENV) else None
    mod = mx.mod.Module(_symbol(), context=[mx.cpu(i) for i in range(ndev)]
                        if ndev > 1 and layout is None else mx.cpu(),
                        data_names=("data",), label_names=("label",),
                        layout=layout)
    kw = {}
    if ckpt_dir is not None:
        kw["checkpoint"] = mx.checkpoint.CheckpointConfig(
            ckpt_dir, every_n_batches=2, period_epochs=1, keep_last=0)
        kw["resume_from"] = elastic.resume_dir(ckpt_dir)
    if check_recompiles:
        def _no_recompiles(_param):
            n = profiler.get_counter("loop_recompile")
            assert n == 0, "steady-state recompile detected (%d)" % n
        kw["batch_end_callback"] = _no_recompiles
    mod.fit(it, num_epoch=EPOCHS, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9},
            **kw)
    if layout is not None:
        # the drill must exercise REAL fsdp sharding, not silently
        # degrade to replicated: weight AND optimizer state shards
        import jax as _jax
        w = mod._exec.arg_dict["lut_weight"].data
        assert layout.fsdp_axis in str(w.sharding.spec), w.sharding
        assert max(s.data.nbytes for s in w.addressable_shards) \
            < w.nbytes, "lut_weight not actually sharded"
        for leaf in _jax.tree_util.tree_leaves(mod._fused_states):
            assert max(s.data.nbytes for s in leaf.addressable_shards) \
                < leaf.nbytes, "optimizer state not sharded"
    arg, _aux = mod.get_params()
    w = {k: v.asnumpy() for k, v in arg.items()}
    if out_path is not None:
        np.savez(out_path, **w)
    return ndev, w


def _child(ckpt_dir, out_path):
    from mxnet_tpu import faults, profiler
    attempt = int(os.environ.get("MXNET_TPU_ELASTIC_ATTEMPT", "0"))
    spec = ATTEMPT_FAULTS.get(attempt)
    if spec:
        faults.install(spec)
    ndev, _w = _train(ckpt_dir=ckpt_dir, out_path=out_path,
                      check_recompiles=True)
    print("ELASTIC-CHILD-DONE world=%d attempt=%d reshard=%d "
          "recompiles=%d"
          % (ndev, attempt, profiler.get_counter("elastic_reshard"),
             profiler.get_counter("loop_recompile")))
    return 0


def _zero_cost():
    from mxnet_tpu import faults, profiler
    assert not faults.ARMED, "fault harness armed with no knob set"
    _train()
    assert profiler.get_counter("fault_injected") == 0
    print("ZERO-COST-OK counters=%s"
          % json.dumps({k: v for k, v in profiler.counters().items()
                        if k.startswith("fault")}))
    return 0


def main():
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        return _child(sys.argv[i + 1], sys.argv[i + 2])
    if "--baseline" in sys.argv:
        _ndev, _w = _train(out_path=sys.argv[sys.argv.index("--baseline")
                                             + 1])
        print("BASELINE-DONE")
        return 0
    if "--zero-cost" in sys.argv:
        return _zero_cost()

    from mxnet_tpu import elastic
    fsdp = "--fsdp" in sys.argv
    work = tempfile.mkdtemp(prefix="elastic_smoke_")
    ckpt_base = os.path.join(work, "ckpts")
    base_npz = os.path.join(work, "baseline.npz")
    elastic_npz = os.path.join(work, "elastic.npz")
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    env.pop("MXNET_TPU_FAULTS", None)
    env.pop("MXNET_TPU_CKPT_TEST_CRASH", None)
    env.pop(FSDP_ENV, None)
    if fsdp:
        env[FSDP_ENV] = "1"

    # ---- uninterrupted 8-device baseline --------------------------------
    flags = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--baseline", base_npz],
        env={**env, "XLA_FLAGS": flags}, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # ---- elastic run: preempt at 8, resume on 4, preempt, finish on 2 ---
    sup = elastic.Supervisor(
        [sys.executable, os.path.abspath(__file__), "--child", ckpt_base,
         elastic_npz],
        world_schedule=WORLD_SCHEDULE, max_restarts=4, backoff=0.05,
        backoff_max=0.2, jitter_seed=0, env=env)
    rc = sup.run()
    assert rc == 0, "supervisor rc=%d" % rc
    assert sup.restarts == 2, "expected 2 restarts, got %d" % sup.restarts
    assert sup.reshards == 2, \
        "expected 2 world-size changes, got %d" % sup.reshards

    # ---- parity ---------------------------------------------------------
    ref = dict(np.load(base_npz))
    got = dict(np.load(elastic_npz))
    assert set(ref) == set(got), (sorted(ref), sorted(got))
    for k in sorted(ref):
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)
    print("kill->reshard->resume parity: 8 -> 4 -> 2 devices%s, "
          "params bit-identical to the uninterrupted 8-device run"
          % (" (dp x fsdp layout, sharded params + opt states)"
             if fsdp else ""))

    # ---- knobs-off zero-cost gate (plain mode only: the fsdp drill's
    # zero-cost story is the multichip smoke's no-layout gate) ----------
    if not fsdp:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--zero-cost"],
            env={**env, "XLA_FLAGS": flags}, capture_output=True,
            text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ZERO-COST-OK" in proc.stdout

    print("ELASTIC-FSDP-DRILL-OK" if fsdp else "ELASTIC-DRILL-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CI ``serve-decode`` job: continuous-batching drill + budget/AOT/gate
checks (ISSUE 16 satellite).

Five checks, all on the tiny zoo transformer, CPU backend:

1. **Continuous-batching drill** — requests join a RUNNING decode batch
   mid-flight, stream per-token, and evict on finish; after the warm
   wave the compile counter must move ZERO and the executable set must
   stay <= |prompt buckets| + |decode buckets|.
2. **Fault matrix** — ``serve.decode@1`` kills exactly ONE sequence's
   future (legible error naming the site + slot) while co-residents
   finish; ``serve.evict@1`` fails the handle but still frees the pages
   (slots_in_use == 0 after).
3. **hbm-budget rejection** — ``MXNET_TPU_ANALYZE=strict`` with a 1K
   budget must reject the cache reservation at server START, naming it.
4. **Zero-cost gate** — a subprocess importing ``mxnet_tpu.serve`` must
   NOT have ``serve.decode`` / ``serve.kv_cache`` in sys.modules.
5. **AOT warm restart** — a second process with
   ``MXNET_TPU_COMPILE_CACHE`` pointing at the first's executables must
   reach its first generated token with ZERO serve-scope backend
   compiles (obs compile accounting), plus the int8 capacity check:
   ``max_slots_for`` doubles under int8 at a fixed budget.

Exit code 0 = all gates passed.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

GEO = dict(vocab_size=128, num_layers=2, d_model=32, n_heads=2, seq_len=32)


def _module():
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer
    net = transformer.get_symbol(**GEO)
    mod = mx.mod.Module(net, context=mx.cpu())
    s = GEO["seq_len"]
    mod.bind(data_shapes=[("data", (1, s))],
             label_shapes=[("softmax_label", (1, s))])
    mx.random.seed(11)
    mod.init_params(mx.init.Uniform(0.05))
    return mod


def check_continuous_batching():
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    mod = _module()
    srv = mx.serve.GenerativeServer(mod, n_heads=GEO["n_heads"],
                                    max_sequences=4, page=8, int8=False,
                                    name="drill")
    try:
        # warm wave: one request per prompt bucket the drill traffic
        # uses, decoding deep enough to touch every decode bucket it
        # reaches (short prompts rung up through bucket 8 and 16; the
        # long one crosses into 32)
        srv.submit_generate([1], max_new_tokens=10).result(timeout=300)
        srv.submit_generate(list(range(1, 12)),
                            max_new_tokens=10).result(timeout=300)
        warm = profiler.get_counter("drill_compile")
        bound = srv.engine.executable_bound()
        assert warm <= bound, (warm, bound)

        # the drill proper: a long-runner, joins mid-flight, streaming
        long_run = srv.submit_generate([1, 2, 3], max_new_tokens=10)
        while not long_run.tokens_so_far():
            time.sleep(0.005)
        streamed = []
        joiner = srv.submit_generate([4, 5], max_new_tokens=6,
                                     on_token=streamed.append)
        late = srv.submit_generate([6], max_new_tokens=4)
        assert len(list(joiner)) == 6          # iterator streaming
        assert streamed == joiner.result(timeout=60)   # callback parity
        assert len(long_run.result(timeout=300)) == 10
        assert len(late.result(timeout=300)) == 4
        assert profiler.get_counter("drill_compile") == warm, \
            "steady-state decode recompiled"
        st = srv.stats()
        assert st["compiles"] <= st["executable_bound"], st
        assert st["kv"]["slots_in_use"] == 0, "pages leaked after evict"
        print("PASS continuous-batching: %d compiles <= bound %d, "
              "0 steady-state recompiles, streams ok"
              % (st["compiles"], st["executable_bound"]))
        return srv, mod
    except BaseException:
        srv.close()
        raise


def check_faults(srv):
    from mxnet_tpu import faults
    from mxnet_tpu.serve import ServeError
    # co-residency setup: once b streams its FIRST token it is resident,
    # and a (still decoding, lower slot) is the deterministic victim.
    # Decode steps are ~1ms here, so a's whole lifetime is a few dozen
    # ms — under GIL scheduling the observer thread can miss the whole
    # window, hence the retry loop.
    for _ in range(10):
        a = srv.submit_generate([1, 2, 3], max_new_tokens=40)
        while not a.tokens_so_far():
            time.sleep(0.001)
        b = srv.submit_generate([4, 5], max_new_tokens=8)
        while not b.tokens_so_far():
            time.sleep(0.0005)
        if not a.done():
            break
        b.result(timeout=300)          # drain the attempt and retry
    else:
        raise AssertionError("never caught a and b co-resident")
    faults.install("serve.decode@1")
    try:
        # the contract: EXACTLY ONE sequence's future dies, with a
        # legible error naming the site; the co-resident completes its
        # full generation (slot reuse is LIFO, so which handle holds
        # the victim slot varies — the batch surviving is the point)
        outcomes = []
        for h, want in ((a, (29, 40)), (b, (8,))):
            try:
                outcomes.append(("ok", h, len(h.result(timeout=300)),
                                 want))
            except ServeError as exc:
                assert "serve.decode" in str(exc), exc
                outcomes.append(("killed", h, None, want))
    finally:
        faults.clear()
    killed = [o for o in outcomes if o[0] == "killed"]
    assert len(killed) == 1, "decode fault killed %d of 2 sequences" \
        % len(killed)
    for kind, _h, n, want in outcomes:
        if kind == "ok":
            assert n in want, "co-resident sequence truncated: %s" % n

    faults.install("serve.evict@1")
    try:
        h = srv.submit_generate([7], max_new_tokens=2)
        try:
            h.result(timeout=300)
            raise AssertionError("injected evict fault did not surface")
        except ServeError as exc:
            assert "pages were still freed" in str(exc), exc
    finally:
        faults.clear()
    st = srv.stats()
    assert st["kv"]["slots_in_use"] == 0, "evict fault leaked pages"
    srv.close()
    print("PASS faults: decode fault killed one stream, evict fault "
          "freed pages")


_BUDGET_CHILD = """
import os, sys
sys.path.insert(0, %(root)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import mxnet_tpu as mx
from mxnet_tpu.models import transformer
net = transformer.get_symbol(**%(geo)r)
mod = mx.mod.Module(net, context=mx.cpu())
s = %(geo)r["seq_len"]
mod.bind(data_shapes=[("data", (1, s))],
         label_shapes=[("softmax_label", (1, s))])
mod.init_params(mx.init.Uniform(0.05))
# strict budget goes on AFTER bind: the drill targets the SERVER-start
# reservation audit, not the bind-time program pass
os.environ["MXNET_TPU_ANALYZE"] = "strict"
os.environ["MXNET_TPU_ANALYZE_HBM_BUDGET"] = "1K"
mx.config.reset("MXNET_TPU_ANALYZE")
mx.config.reset("MXNET_TPU_ANALYZE_HBM_BUDGET")
try:
    mx.serve.GenerativeServer(mod, n_heads=%(geo)r["n_heads"],
                              max_sequences=8, page=8, name="overbudget")
except mx.base.MXNetError as exc:
    msg = str(exc)
    assert "hbm-budget" in msg, msg
    assert "overbudget_kv_cache" in msg, msg  # the reservation is NAMED
    print("BUDGET-REJECTED")
else:
    raise AssertionError("1K budget admitted the KV reservation")
"""


def check_budget_rejection():
    out = subprocess.run(
        [sys.executable, "-c",
         _BUDGET_CHILD % {"root": _ROOT, "geo": GEO}],
        capture_output=True, text=True, timeout=600, env=dict(os.environ))
    assert "BUDGET-REJECTED" in out.stdout, out.stdout + out.stderr
    print("PASS hbm-budget: strict 1K budget rejected the reservation "
          "naming it")


_GATE_CHILD = """
import sys
sys.path.insert(0, %(root)r)
import mxnet_tpu
import mxnet_tpu.serve
bad = [m for m in sys.modules
       if m in ("mxnet_tpu.serve.decode", "mxnet_tpu.serve.kv_cache")]
assert not bad, bad
print("GATE-OK")
"""


def check_zero_cost_gate():
    out = subprocess.run(
        [sys.executable, "-c", _GATE_CHILD % {"root": _ROOT}],
        capture_output=True, text=True, timeout=600, env=dict(os.environ))
    assert "GATE-OK" in out.stdout, out.stdout + out.stderr
    print("PASS zero-cost gate: decode path unimported when unused")


_AOT_CHILD = """
import os, sys, json
sys.path.insert(0, %(root)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import mxnet_tpu as mx
from mxnet_tpu.models import transformer
net = transformer.get_symbol(**%(geo)r)
mod = mx.mod.Module(net, context=mx.cpu())
s = %(geo)r["seq_len"]
mod.bind(data_shapes=[("data", (1, s))],
         label_shapes=[("softmax_label", (1, s))])
import numpy as np
np.random.seed(11)     # initializers draw from global np.random: seeding
mod.init_params(mx.init.Uniform(0.05))   # it makes params identical
srv = mx.serve.GenerativeServer(mod, n_heads=%(geo)r["n_heads"],  # across
                                max_sequences=2, page=8,     # processes
                                name="warmdrill")
toks = srv.submit_generate([3, 1, 4], max_new_tokens=4).result(timeout=300)
srv.close()
snap = mx.obs.report()
backend = [c for c in snap["compiles"] if c.get("scope") == "warmdrill"]
print(json.dumps({"tokens": toks, "backend_compiles": len(backend)}))
"""


def check_aot_warm_restart():
    cache_dir = tempfile.mkdtemp(prefix="serve_decode_aot_")
    env = dict(os.environ)
    env["MXNET_TPU_COMPILE_CACHE"] = cache_dir
    code = _AOT_CHILD % {"root": _ROOT, "geo": GEO}
    runs = []
    for _ in range(2):
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr
        runs.append(json.loads(out.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    assert cold["backend_compiles"] > 0, \
        "cold run compiled nothing — the drill is not measuring"
    assert warm["backend_compiles"] == 0, \
        "warm restart compiled %d serve programs" % warm["backend_compiles"]
    assert warm["tokens"] == cold["tokens"], \
        "AOT executable decoded different tokens"
    print("PASS aot warm restart: first token with 0 backend compiles "
          "(cold run had %d)" % cold["backend_compiles"])

    from mxnet_tpu.serve.kv_cache import max_slots_for
    geo = dict(num_layers=4, n_heads=8, d_head=64, max_seq=2048, page=16)
    budget = 8 * 1024 ** 3
    f32 = max_slots_for(budget, int8=False, **geo)
    i8 = max_slots_for(budget, int8=True, **geo)
    assert i8 >= 2 * f32, (f32, i8)
    print("PASS int8 capacity: %d -> %d resident sequences under the "
          "same budget" % (f32, i8))


def main():
    srv, _ = check_continuous_batching()
    check_faults(srv)
    check_budget_rejection()
    check_zero_cost_gate()
    check_aot_warm_restart()
    print("serve-decode smoke: ALL PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Run a Predictor.export() artifact with nothing but jax installed.

This is the deployment half of the amalgamation story (the reference
ships a single-file predict-only build, amalgamation/Makefile +
c_predict_api.h): the artifact zip holds a serialized StableHLO program,
the frozen weights, and a manifest — no framework import happens here.

  python tools/predict_exported.py model.mxprog --input data=batch.npy
  python tools/predict_exported.py model.mxprog          # random inputs
"""
import argparse
import io
import json
import sys
import zipfile

import numpy as np


def load_artifact(path):
    """Returns (call, manifest): ``call(**inputs) -> list of np arrays``."""
    from jax import export as jexport

    with zipfile.ZipFile(path) as z:
        manifest = json.loads(z.read("manifest.json"))
        if manifest.get("format") != "mxnet_tpu.exported/1":
            raise ValueError("not a mxnet_tpu export artifact: %s" % path)
        exported = jexport.deserialize(z.read("program.stablehlo"))
        with np.load(io.BytesIO(z.read("weights.npz"))) as wz:
            weights = {k: wz[k] for k in wz.files}

    def call(**inputs):
        missing = [n for n in manifest["inputs"] if n not in inputs]
        if missing:
            raise ValueError("missing inputs: %s" % missing)
        flat = [weights[n] for n in manifest["weights"]]
        flat += [np.asarray(inputs[n]) for n in manifest["inputs"]]
        return [np.asarray(o) for o in exported.call(*flat)]

    return call, manifest


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("artifact")
    p.add_argument("--input", action="append", default=[],
                   metavar="name=path.npy",
                   help="input tensor from an .npy file; unspecified "
                        "inputs get seeded random data")
    args = p.parse_args()

    call, manifest = load_artifact(args.artifact)
    feeds = {}
    for spec in args.input:
        name, path = spec.split("=", 1)
        feeds[name] = np.load(path)
    rng = np.random.RandomState(0)
    for name in manifest["inputs"]:
        if name not in feeds:
            feeds[name] = rng.uniform(
                -1, 1, manifest["input_shapes"][name]).astype(np.float32)
    outs = call(**feeds)
    for i, o in enumerate(outs):
        print("output[%d] shape=%s dtype=%s mean=%.6f" %
              (i, o.shape, o.dtype, float(np.mean(o))))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Kill stray training processes on the local host or a cluster.

Reference: ``tools/kill-mxnet.py`` (ssh to every host in a hostfile and
pkill leftover workers after a crashed distributed job). Same semantics:
match processes whose command line contains the given program name (and
a DMLC_ROLE env marker when --dmlc-only), SIGTERM then SIGKILL.

Usage:
  python tools/kill_mxnet.py train.py                # local
  python tools/kill_mxnet.py -H hosts train.py       # every host in file
"""
import argparse
import os
import signal
import subprocess
import sys
import time


def _local_pids(pattern):
    out = subprocess.run(["pgrep", "-f", pattern], capture_output=True,
                         text=True)
    me = os.getpid()
    return [int(p) for p in out.stdout.split()
            if p.strip() and int(p) != me]


def kill_local(pattern, grace=3.0):
    pids = _local_pids(pattern)
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline and _local_pids(pattern):
        time.sleep(0.2)
    for pid in _local_pids(pattern):
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    return pids


def main():
    ap = argparse.ArgumentParser(description="kill leftover workers")
    ap.add_argument("program", help="command-line substring to match")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line; ssh to each (reference "
                         "kill-mxnet.py behavior)")
    args = ap.parse_args()

    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
        for host in hosts:
            subprocess.run(
                ["ssh", "-o", "StrictHostKeyChecking=no", host,
                 "pkill", "-f", args.program], check=False)
            print("signalled %s on %s" % (args.program, host))
        return 0
    pids = kill_local(args.program)
    print("killed %d process(es) matching %r" % (len(pids), args.program))
    return 0


if __name__ == "__main__":
    sys.exit(main())

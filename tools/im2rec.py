#!/usr/bin/env python
"""im2rec CLI — thin launcher over the packaged implementation
(mxnet_tpu/tools/im2rec.py; reference tools/im2rec.py / im2rec.cc)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.tools.im2rec import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

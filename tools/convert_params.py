"""Convert NDArray checkpoints between the reference binary .params
layout and this framework's npz container (both readable by mx.nd.load).

Capability twin of the reference model-store tooling
(python/mxnet/gluon/model_zoo/model_store.py + the checkpoint formats of
model.save_checkpoint): existing MXNet .params files work here directly
(nd.load autodetects), and this tool re-encodes in either direction —
e.g. to ship a TPU-trained checkpoint back to a reference deployment.

  python tools/convert_params.py model.params out.npz
  python tools/convert_params.py ckpt.npz out.params --format mxnet
  python tools/convert_params.py ckpt.params out.params --strip-prefix
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("src", help="input checkpoint (.params binary or npz)")
    p.add_argument("dst", help="output path")
    p.add_argument("--format", choices=("npz", "mxnet"), default=None,
                   help="output container; default: mxnet for .params "
                        "destinations, npz otherwise")
    p.add_argument("--strip-prefix", action="store_true",
                   help="drop arg:/aux: key prefixes (module checkpoint "
                        "-> gluon-style flat names)")
    p.add_argument("--add-prefix", choices=("arg", "aux"), default=None,
                   help="prefix every key (flat names -> module-style)")
    args = p.parse_args()

    import mxnet_tpu as mx

    data = mx.nd.load(args.src)
    if isinstance(data, list):
        if args.strip_prefix or args.add_prefix:
            p.error("prefix options need a named checkpoint")
    else:
        if args.strip_prefix:
            from mxnet_tpu.ndarray.legacy_format import strip_arg_aux
            data = strip_arg_aux(data)
        if args.add_prefix:
            data = {"%s:%s" % (args.add_prefix, k): v
                    for k, v in data.items()}
    fmt = args.format or ("mxnet" if args.dst.endswith(".params")
                          else "npz")
    mx.nd.save(args.dst, data, format=fmt)
    n = len(data)
    print("wrote %s (%d arrays, %s container)" % (args.dst, n, fmt))
    return 0


if __name__ == "__main__":
    sys.exit(main())

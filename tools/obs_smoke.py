"""CI ``obs`` job: trace-schema + exposition validation and the
disabled-mode overhead gate (ISSUE 6 satellite).

Three checks, all pure Python, no external scrapers or viewers:

1. **Trace schema** — a short async ``fit`` with ``MXNET_TPU_OBS=1``
   must dump a Perfetto-loadable ``{"traceEvents": [...]}`` with >= 4
   distinct named lanes and at least one batch flow id linking >= 3
   lanes (prefetch -> device-place -> train/metric).
2. **Exposition** — ``mx.obs.render_prometheus()`` must pass the strict
   pure-Python text-format grammar check (``parse_prometheus``), and the
   always-on compile telemetry (obs_compile_count / obs_bind_ms) must be
   populated by the fit's binds.
3. **Disabled-mode overhead gate** — a subprocess with ``MXNET_TPU_OBS``
   off runs the same fixed-step fused loop and must record ZERO span
   allocations (``obs_spans`` counter — deterministic, the principled
   gate: disabled span() returns a shared no-op). The enabled subprocess
   must stay within a generous noise band of the disabled one (CI boxes
   are noisy; the 1%-class claim is measured on quiet hardware by
   tools/perf/fit_loop_bench.py comparisons, not here).

Exit code 0 = all gates passed.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = """
import json, os, sys, time
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx

mod_sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
    mx.sym.Variable("data"), num_hidden=32, name="fc1"), name="softmax")
mod = mx.mod.Module(mod_sym, context=mx.cpu())
mod.bind(data_shapes=[("data", (16, 8))],
         label_shapes=[("softmax_label", (16,))])
mod.init_params(mx.init.Xavier())
mod.init_optimizer(optimizer="sgd",
                   optimizer_params={"learning_rate": 0.1})
rng = np.random.RandomState(0)
db = mx.io.DataBatch(data=[mx.nd.array(rng.rand(16, 8).astype(np.float32))],
                     label=[mx.nd.array(np.zeros((16,), np.float32))])
import jax
from mxnet_tpu import profiler as _profiler
for _ in range(3):
    mod._fit_step(db)
jax.block_until_ready(mod._step_token())
with _profiler.counter_delta() as d:
    n = 300
    t0 = time.perf_counter()
    for i in range(n):
        # the per-batch span exactly as fit()'s hot loop carries it:
        # disabled mode must make this a shared no-op (zero allocations)
        with _profiler.span("fused_step_dispatch", "step", flow=i):
            mod._fit_step(db)
    jax.block_until_ready(mod._step_token())
    dt = time.perf_counter() - t0
print(json.dumps({"steps_per_sec": n / dt, "spans": d.get("obs_spans")}))
"""


def _run_child(obs_on: bool) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_TPU_OBS"] = "1" if obs_on else "0"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", _CHILD % {"root": root}],
        env=env, stdout=subprocess.PIPE, text=True, timeout=300, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])


def check_trace_and_exposition() -> None:
    import numpy as np
    import mxnet_tpu as mx

    mx.config.set("MXNET_TPU_OBS", 1)
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (160, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=8, name="fc1"), name="softmax")
    with tempfile.TemporaryDirectory() as td:
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.fit(it, optimizer="sgd", initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.1}, num_epoch=2,
                checkpoint=mx.checkpoint.CheckpointConfig(
                    os.path.join(td, "ck"), every_n_batches=5))
        mx.config.set("MXNET_TPU_OBS", 0)
        path = os.path.join(td, "trace.json")
        mx.profiler.set_config(filename=path)
        mx.profiler.dump()
        with open(path) as f:
            trace = json.load(f)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events, "empty trace"
    lanes = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert len(lanes) >= 4, "expected >=4 named lanes, got %s" % lanes
    flow_lanes = {}
    for e in events:
        if e.get("cat") == "flow":
            flow_lanes.setdefault(e["id"], set()).add(e["tid"])
    assert any(len(v) >= 3 for v in flow_lanes.values()), \
        "no flow id crossed >=3 lanes"
    print("obs_smoke: trace OK — lanes=%s flows=%d"
          % (sorted(lanes), len(flow_lanes)))

    text = mx.obs.render_prometheus()
    samples = mx.obs.parse_prometheus(text)
    assert samples, "empty exposition"
    assert ("mxnet_tpu_obs_compile_count_total", ()) in samples, \
        "compile telemetry missing from exposition"
    assert mx.obs.histogram("obs_bind_ms").count > 0, \
        "obs_bind_ms histogram never populated"
    print("obs_smoke: exposition OK — %d samples parse" % len(samples))


def check_disabled_overhead() -> None:
    off = _run_child(obs_on=False)
    on = _run_child(obs_on=True)
    print("obs_smoke: steps/s off=%.1f on=%.1f, off-mode spans=%d"
          % (off["steps_per_sec"], on["steps_per_sec"], off["spans"]))
    assert off["spans"] == 0, \
        "disabled mode allocated %d spans" % off["spans"]
    assert on["spans"] > 0, "enabled mode recorded no spans"
    # generous CI noise band; the deterministic gate is the zero-span
    # assert above
    assert on["steps_per_sec"] >= 0.5 * off["steps_per_sec"], \
        "enabled-mode overhead out of band"


def main() -> None:
    check_trace_and_exposition()
    check_disabled_overhead()
    print("obs_smoke: ALL OK")


if __name__ == "__main__":
    main()

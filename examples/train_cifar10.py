"""Train a small ResNet on a generated CIFAR-like dataset, end to end.

Capability twin of the reference's
``example/image-classification/train_cifar10.py``: a ResNet built for
32x32 color images trained through the shared fit harness with the
random-crop/mirror RecordIO augmentation pipeline (the C++ native path
when available). Downloads are disabled here, so the dataset is
deterministic synthetic color textures (10 classes by hue/stripe
pattern), learnable to high accuracy.

Run:  python examples/train_cifar10.py --num-epochs 8
"""
import argparse
import atexit
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import fit as fit_mod

NUM_CLASSES = 10


def synth_cifar(n=2000, seed=0):
    """32x32x3 textures: class = dominant hue pair + stripe direction."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, NUM_CLASSES, n)
    x = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.3
    yy, xx = np.mgrid[0:32, 0:32]
    hstripe = ((yy // 4) % 2).astype(np.float32)
    vstripe = ((xx // 4) % 2).astype(np.float32)
    for c in range(NUM_CLASSES):
        idx = y == c
        x[idx, c % 3] += 0.4
        x[idx, (c // 3) % 3] += 0.3 * (hstripe if c % 2 else vstripe)
    return np.clip(x, 0, 1), y.astype(np.float32)


def _pack_rec(x, y, path):
    import cv2
    from mxnet_tpu import recordio
    rec = recordio.MXRecordIO(path, "w")
    for i in range(x.shape[0]):
        img = (x[i].transpose(1, 2, 0)[:, :, ::-1] * 255).astype(np.uint8)
        ok, enc = cv2.imencode(".png", img)
        rec.write(recordio.pack(
            recordio.IRHeader(0, float(y[i]), i, 0), enc.tobytes()))
    rec.close()


def data_loader(args, kv):
    import mxnet_tpu as mx
    x, y = synth_cifar(args.num_examples, seed=11)
    split = int(0.9 * len(y))
    d = tempfile.mkdtemp()
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    _pack_rec(x[:split], y[:split], os.path.join(d, "train.rec"))
    _pack_rec(x[split:], y[split:], os.path.join(d, "val.rec"))
    train = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(d, "train.rec"),
        data_shape=(3, 28, 28), batch_size=args.batch_size, shuffle=True,
        rand_crop=True, rand_mirror=True, scale=1.0 / 255)
    val = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(d, "val.rec"),
        data_shape=(3, 28, 28), batch_size=args.batch_size,
        scale=1.0 / 255)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train cifar10-style")
    fit_mod.add_fit_args(parser)
    parser.add_argument("--num-examples", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=42)
    # the lr decay (reference train_cifar10.py default
    # --lr-step-epochs) is what makes the FINAL epoch the converged
    # one: flat lr=0.1 SGD oscillates epoch-to-epoch on the tiny val
    # set (NIGHTLY_r04 run-2 flake), decayed SGD settles
    parser.set_defaults(network="resnet", num_epochs=8, lr=0.1,
                        lr_step_epochs="4,6", batch_size=100,
                        disp_batches=10)
    args = parser.parse_args()
    np.random.seed(args.seed)     # initializers draw from the global RNG
    import mxnet_tpu as mx
    mx.random.seed(args.seed)

    from mxnet_tpu.models import resnet
    # resnet-8 for 32x32 inputs (reference train_cifar10 uses the
    # small-image resnet variant)
    net = resnet.get_symbol(num_classes=NUM_CLASSES, num_layers=8,
                            image_shape="3,28,28")

    cache = {}

    def loader(a, kv):
        if "iters" not in cache:
            cache["iters"] = data_loader(a, kv)
        return cache["iters"]

    mod = fit_mod.fit(args, net, loader)
    _, val = cache["iters"]
    val.reset()
    score = mod.score(val, "acc")
    # FINAL-epoch accuracy is the contract (reference
    # tests/python/train: convergence, not a mid-run peak); the seeded
    # run with lr decay makes it deterministic
    print("final validation accuracy: %.4f" % score[0][1])
    assert score[0][1] > 0.85, "failed to learn the synthetic textures"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Large-margin classification with SVMOutput.

Capability twin of the reference's ``example/svm_mnist``: the same conv
features, but the loss head is ``SVMOutput`` (multiclass hinge loss, L1
or squared L2) instead of softmax cross-entropy — the reference op's
margin semantics (`src/operator/svm_output.cc`) driving a Module fit.
The gate compares both SVM variants against the softmax head on the
same synthetic digits: all three must clear the accuracy bar.

Run:  python examples/svm_mnist.py --num-epochs 4
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth_digits(n, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 16, 16).astype(np.float32) * 0.25
    for c in range(10):
        r, co = divmod(c, 4)
        x[y == c, 0, 4 * r:4 * r + 4, 4 * co:4 * co + 4] += 0.65
    return np.clip(x, 0, 1), y.astype(np.float32)


def build(head, margin=1.0, reg=1.0):
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                           name="c1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc")
    label = mx.sym.Variable("softmax_label")
    if head == "softmax":
        return mx.sym.SoftmaxOutput(h, label, name="softmax")
    return mx.sym.SVMOutput(h, label, margin=margin,
                            regularization_coefficient=reg,
                            use_linear=(head == "l1-svm"), name="svm")


def run(head, X, Y, Xv, Yv, args):
    import mxnet_tpu as mx
    mod = mx.mod.Module(build(head), context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (args.batch_size, 1, 16, 16))],
             label_shapes=[("softmax_label", (args.batch_size,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    it = mx.io.NDArrayIter(X, Y, args.batch_size, shuffle=True,
                           label_name="softmax_label")
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
    vit = mx.io.NDArrayIter(Xv, Yv, args.batch_size,
                            label_name="softmax_label")
    score = mod.score(vit, "acc")
    return float(score[0][1])


def main():
    p = argparse.ArgumentParser(description="SVM heads vs softmax")
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--num-examples", type=int, default=1500)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    X, Y = synth_digits(args.num_examples, seed=1)
    Xv, Yv = synth_digits(300, seed=2)
    for head in ("l2-svm", "l1-svm", "softmax"):
        acc = run(head, X, Y, Xv, Yv, args)
        print("%-8s accuracy: %.4f" % (head, acc))
        assert acc > 0.9, "%s failed to learn" % head
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""ImageNet-class training CLI over RecordIO — the BASELINE north-star
entry point.

Capability twin of the reference's
``example/image-classification/train_imagenet.py``: the same flag
surface (``--network --num-layers --batch-size --kv-store --lr
--lr-step-epochs --data-train ...`` via ``common/fit.py`` +
``common/data.py``), symbol networks selected by name, RecordIO input
through the C++ image pipeline, checkpointing, and dist training via
``--kv-store dist_sync`` under ``tools/launch.py``.

Typical invocations:

  # real data (pack with tools/im2rec.py)
  python examples/train_imagenet.py --network resnet --num-layers 50 \
      --data-train train.rec --data-val val.rec --batch-size 256 \
      --lr 0.1 --lr-step-epochs 30,60,90

  # synthetic-data benchmark mode (reference --benchmark parity)
  python examples/train_imagenet.py --network resnet --num-layers 18 \
      --benchmark 1 --num-classes 100 --image-shape 3,64,64 \
      --num-epochs 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import data as data_mod
from common import fit as fit_mod


def get_network(args):
    from mxnet_tpu.models import alexnet, lenet, mlp, resnet, vgg
    from mxnet_tpu.models import inception
    name = args.network
    kw = dict(num_classes=args.num_classes,
              image_shape=args.image_shape)
    # per-network depth defaults (reference train_imagenet defaults)
    layers = args.num_layers
    if layers is None:
        layers = {"resnet": 50, "vgg": 16}.get(name)
    if name == "resnet":
        return resnet.get_symbol(num_layers=layers, stem=args.stem, **kw)
    if name == "vgg":
        return vgg.get_symbol(num_layers=layers, **kw)
    if name == "alexnet":
        return alexnet.get_symbol(num_classes=args.num_classes)
    if name in ("inception-bn", "inception_bn"):
        return inception.get_symbol(num_classes=args.num_classes,
                                    version="bn")
    if name in ("inception-v3", "inception_v3"):
        return inception.get_symbol(num_classes=args.num_classes,
                                    version="v3")
    if name == "lenet":
        return lenet.get_symbol(num_classes=args.num_classes)
    if name == "mlp":
        return mlp.get_symbol(num_classes=args.num_classes)
    raise ValueError("unknown --network %r" % name)


def main():
    parser = argparse.ArgumentParser(
        description="train on imagenet-class RecordIO data",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit_mod.add_fit_args(parser)
    data_mod.add_data_args(parser)
    parser.add_argument("--num-layers", type=int, default=None,
                        help="network depth (default: resnet 50, vgg 16)")
    parser.add_argument("--stem", type=str, default="7x7",
                        choices=["7x7", "s2d"],
                        help="resnet stem lowering (s2d = space-to-depth"
                             ", the TPU-optimized identical transform)")
    parser.set_defaults(network="resnet",
                        # reference train_imagenet defaults
                        num_epochs=80, lr=0.1, lr_factor=0.1,
                        lr_step_epochs="30,60", batch_size=128,
                        wd=1e-4)
    args = parser.parse_args()

    net = get_network(args)
    cache = {}

    def loader(a, kv):
        cache["iters"] = data_mod.get_rec_iters(a, kv)
        return cache["iters"]

    mod = fit_mod.fit(args, net, loader)
    val = cache["iters"][1]
    if val is not None:
        val.reset()
        score = mod.score(val, "acc")
        print("final validation accuracy: %.4f" % score[0][1])
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Stacked autoencoder with layer-wise pretraining then fine-tuning.

Capability twin of the reference's ``example/autoencoder`` (Xie et al.'s
DEC pretraining stage: greedy layer-wise denoising pretrain, then
end-to-end fine-tune). Data is a mixture of low-rank gaussian clusters,
so reconstruction error has a known floor well below the identity-free
baseline (predicting the mean).

Run:  python examples/autoencoder.py --num-epochs 12
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DIM = 32


def synth_data(n, seed=0):
    """Points near a 4-dim linear manifold inside DIM dims + noise."""
    rng = np.random.RandomState(seed)
    basis = rng.randn(4, DIM).astype(np.float32)
    codes = rng.randn(n, 4).astype(np.float32)
    return codes @ basis + 0.05 * rng.randn(n, DIM).astype(np.float32)


def main():
    p = argparse.ArgumentParser(description="stacked autoencoder")
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--pretrain-epochs", type=int, default=4)
    p.add_argument("--num-examples", type=int, default=1500)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=5)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, Trainer
    np.random.seed(args.seed)

    x = synth_data(args.num_examples)
    n_val = args.num_examples // 5
    tr, va = x[n_val:], x[:n_val]

    dims = [DIM, 16, 4]
    encoders = [nn.Dense(dims[i + 1], activation=None if i == len(dims) - 2
                         else "relu", in_units=dims[i])
                for i in range(len(dims) - 1)]
    decoders = [nn.Dense(dims[i], activation=None if i == 0 else "relu",
                         in_units=dims[i + 1])
                for i in range(len(dims) - 1)]
    for blk in encoders + decoders:
        blk.initialize(mx.init.Xavier())

    def run_epochs(param_blocks, fwd, epochs, data, tag):
        trainer = Trainer(sum([list(b.collect_params().values())
                               for b in param_blocks], []),
                          "adam", {"learning_rate": args.lr})
        nb = len(data) // args.batch_size
        if nb < 1:
            p.error("--batch-size %d exceeds the %d-row training slice"
                    % (args.batch_size, len(data)))
        for ep in range(epochs):
            tot = 0.0
            for b in range(nb):
                xb = mx.nd.array(data[b * args.batch_size:
                                      (b + 1) * args.batch_size])
                with mx.autograd.record():
                    loss = mx.nd.mean(mx.nd.square(fwd(xb) - xb))
                loss.backward()
                trainer.step(args.batch_size)
                tot += float(loss.asnumpy())
            print("%s epoch[%d] mse=%.5f" % (tag, ep, tot / nb),
                  flush=True)

    # --- greedy layer-wise pretraining (reference autoencoder.py
    # layerwise_pretrain): train (enc_i, dec_i) on the frozen encoding
    feats = tr
    for i, (enc, dec) in enumerate(zip(encoders, decoders)):
        run_epochs([enc, dec], lambda z, e=enc, d=dec: d(e(z)),
                   args.pretrain_epochs,
                   feats, "pretrain-layer%d" % i)
        feats = enc(mx.nd.array(feats)).asnumpy()

    # --- end-to-end fine-tune of the full stack
    def full(z):
        h = z
        for enc in encoders:
            h = enc(h)
        for dec in reversed(decoders):
            h = dec(h)
        return h

    run_epochs(encoders + decoders, full, args.num_epochs, tr, "finetune")

    rec = full(mx.nd.array(va)).asnumpy()
    mse = float(np.mean((rec - va) ** 2))
    base = float(np.mean((va - tr.mean(0)) ** 2))
    print("val mse=%.5f mean-baseline=%.5f" % (mse, base))
    assert mse < base * 0.2, "autoencoder failed to learn the manifold"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CNN text classification (Kim 2014) via the Module API.

Capability twin of the reference's ``example/cnn_text_classification``:
embedding -> parallel conv branches with window sizes 3/4/5 -> max-over-
time pooling -> concat -> dropout -> softmax. The corpus is synthetic:
class-indicative token patterns embedded in noise, so the gate (val
accuracy well above chance) is deterministic.

Run:  python examples/cnn_text_classification.py --num-epochs 6
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB, NCLASS, SEQ = 60, 4, 24


def synth_corpus(n, seed=0):
    """Each class plants one of its two signature trigrams somewhere in a
    noise sequence."""
    rng = np.random.RandomState(seed)
    sigs = {c: [(10 + 3 * c + np.arange(3)) % VOCAB,
                (30 + 3 * c + np.arange(3)) % VOCAB]
            for c in range(NCLASS)}
    x = rng.randint(0, VOCAB, (n, SEQ))
    y = rng.randint(0, NCLASS, n)
    for i in range(n):
        pos = rng.randint(0, SEQ - 2)   # inclusive last start SEQ-3
        x[i, pos:pos + 3] = sigs[y[i]][rng.randint(2)]
    return x.astype(np.float32), y.astype(np.float32)


def get_symbol(num_embed=32, num_filter=32, dropout=0.3):
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")                     # (N, SEQ)
    emb = mx.sym.Embedding(data, mx.sym.Variable("embed_weight"),
                           input_dim=VOCAB, output_dim=num_embed,
                           name="embed")               # (N, SEQ, E)
    emb = mx.sym.reshape(emb, (-1, 1, SEQ, num_embed))  # NCHW
    pooled = []
    for ws in (3, 4, 5):
        c = mx.sym.Convolution(emb, kernel=(ws, num_embed),
                               num_filter=num_filter,
                               name="conv%d" % ws)     # (N, F, SEQ-ws+1, 1)
        c = mx.sym.Activation(c, act_type="relu")
        c = mx.sym.Pooling(c, kernel=(SEQ - ws + 1, 1), pool_type="max",
                           name="pool%d" % ws)         # (N, F, 1, 1)
        pooled.append(c)
    h = mx.sym.Concat(*pooled)                         # (N, 3F, 1, 1)
    h = mx.sym.Flatten(h)
    h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=NCLASS, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def main():
    p = argparse.ArgumentParser(description="Kim-CNN text classification")
    p.add_argument("--num-epochs", type=int, default=6)
    p.add_argument("--num-examples", type=int, default=1200)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=3)
    args = p.parse_args()

    import mxnet_tpu as mx
    np.random.seed(args.seed)

    x, y = synth_corpus(args.num_examples)
    n_val = args.num_examples // 6
    train = mx.io.NDArrayIter(x[n_val:], y[n_val:],
                              batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(x[:n_val], y[:n_val],
                            batch_size=args.batch_size)

    mod = mx.mod.Module(get_symbol(), context=mx.cpu(0)
                        if not mx.num_devices("tpu") else mx.tpu(0))
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(), eval_metric="acc",
            num_epoch=args.num_epochs)
    val.reset()
    score = mod.score(val, "acc")[0][1]
    print("final validation accuracy: %.4f (chance %.2f)"
          % (score, 1.0 / NCLASS))
    assert score > 0.7, "text CNN failed to find the signature trigrams"
    return 0


if __name__ == "__main__":
    sys.exit(main())

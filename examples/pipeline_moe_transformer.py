"""Pipeline-parallel MoE transformer LM: PipelineModule + sym.MoE on a
device mesh.

The modern-parallelism showcase CLI: a decoder-only transformer split
into GPipe pipeline stages over a ``pipe`` mesh axis (embedding adapter,
N body stages of attention + mixture-of-experts FFN blocks, loss head),
trained with microbatch gradient accumulation — the TPU-native
first-class version of the reference's hand-placed inter-layer model
parallelism (``example/model-parallel-lstm/lstm.py:65-129`` +
``group2ctx``, src/executor/graph_executor.cc:279-393).

The task is next-token prediction on a deterministic cyclic corpus, so
falling perplexity proves the pipelined gradients are real.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/pipeline_moe_transformer.py --stages 4 --experts 4
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB = 16


def synth_batches(batch, seq_len, n_batches, seed=0):
    """Cyclic 0..9 token stream with noise tokens 10..15; the cycle makes
    next-token prediction learnable to low perplexity."""
    rng = np.random.RandomState(seed)
    toks = []
    while len(toks) < batch * (seq_len + 1) * n_batches:
        toks.extend(range(10))
        if rng.rand() < 0.3:
            toks.append(10 + rng.randint(6))
    toks = np.asarray(toks, np.int32)
    out = []
    per = batch * (seq_len + 1)
    for i in range(n_batches):
        seg = toks[i * per:(i + 1) * per].reshape(batch, seq_len + 1)
        out.append((seg[:, :-1].astype(np.float32),
                    seg[:, 1:].astype(np.float32)))
    return out


def main():
    p = argparse.ArgumentParser(description="pipelined MoE transformer LM")
    p.add_argument("--stages", type=int, default=4,
                   help="pipeline body stages (devices on the pipe axis)")
    p.add_argument("--layers-per-stage", type=int, default=1)
    p.add_argument("--experts", type=int, default=4,
                   help="MoE experts per block (0 = dense FFN)")
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--microbatches", type=int, default=8)
    p.add_argument("--num-batches", type=int, default=30)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--schedule", choices=["gpipe", "1f1b"],
                   default="gpipe",
                   help="gpipe (autodiff, all-fwd-then-all-bwd) or 1f1b "
                        "(hand-scheduled, O(stages) activation memory)")
    p.add_argument("--ffn-widths", default=None,
                   help="comma list of per-stage FFN widths (unequal "
                        "stages -> heterogeneous pipeline), e.g. "
                        "'256,128,128,64'")
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer

    d_ff = None
    if args.ffn_widths:
        d_ff = [int(w) for w in args.ffn_widths.split(",")]
    stages = transformer.get_pipeline_stages(
        vocab_size=VOCAB, n_stages=args.stages,
        layers_per_stage=args.layers_per_stage, d_model=args.d_model,
        n_heads=args.n_heads, seq_len=args.seq_len, d_ff=d_ff,
        moe_experts=args.experts)
    mod = mx.mod.PipelineModule(stages, n_microbatches=args.microbatches,
                                schedule=args.schedule)
    mod.bind(data_shapes=[("data", (args.batch_size, args.seq_len))],
             label_shapes=[("softmax_label",
                            (args.batch_size, args.seq_len))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9,
                                         "clip_gradient": 1.0})

    batches = synth_batches(args.batch_size, args.seq_len,
                            args.num_batches)
    first_ppl = last_ppl = None
    for epoch in range(args.num_epochs):
        tic = time.time()
        tot_nll = tot_tok = 0.0
        for x, y in batches:
            db = mx.io.DataBatch(data=[mx.nd.array(x)],
                                 label=[mx.nd.array(y)])
            outs = mod.fit_step(db)           # (M, mb*T, V) probs
            probs = np.asarray(outs).reshape(-1, VOCAB)
            labels = y.reshape(-1).astype(int)
            pick = np.maximum(probs[np.arange(labels.size), labels], 1e-9)
            tot_nll += -np.log(pick).sum()
            tot_tok += labels.size
        ppl = math.exp(tot_nll / tot_tok)
        if first_ppl is None:
            first_ppl = ppl
        last_ppl = ppl
        print("Epoch[%d] ppl=%.2f (%.1fs)" % (epoch, ppl,
                                              time.time() - tic),
              flush=True)
    print("final-ppl=%.3f uniform=%.1f" % (last_ppl, VOCAB))
    assert last_ppl < first_ppl, "pipelined training did not learn"
    return 0


if __name__ == "__main__":
    sys.exit(main())

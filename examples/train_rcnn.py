"""Fast R-CNN-style region classifier on generated box data.

Capability twin of the reference's ``example/rcnn`` stack: a conv
backbone, region proposals fed through ``ROIPooling``, and — like the
reference's rcnn, which wires python ops into the graph — a ``CustomOp``
(``proposal_target``) that assigns each ROI its class label by IoU with
the ground-truth box at graph-execution time. Training uses
jittered-ground-truth + random background proposals (classic Fast R-CNN
with precomputed proposals); evaluation asserts ROI classification
accuracy, and an RPN-style ``Proposal``-op pass shows the detection ops
compose.

Backend constraint: ``proposal_target`` uses the host-callback CustomOp
path (arbitrary numpy at graph-execution time), which remote-tunnel TPU
plugins reject — on such rigs this example runs on the CPU backend.
Hot-loop custom ops should implement ``forward_traced`` instead
(docs/new_op.md §1b) to stay device-resident.

Run:  python examples/train_rcnn.py --num-epochs 25
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_CLASSES = 3     # foreground classes; 0 is background
SIZE = 64
R = 8               # rois per image


def synth_rois(n=200, seed=0):
    """Images with one colored rectangle; per image R proposals = jittered
    copies of the gt box (foreground) + random boxes (background)."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 3, SIZE, SIZE).astype(np.float32) * 0.25
    rois = np.zeros((n, R, 4), np.float32)        # pixel corners
    gt = np.zeros((n, 5), np.float32)             # [cls, x1, y1, x2, y2]
    for i in range(n):
        cls = rng.randint(0, NUM_CLASSES)
        w = rng.randint(SIZE // 4, SIZE // 2)
        h = rng.randint(SIZE // 4, SIZE // 2)
        x0 = rng.randint(0, SIZE - w)
        y0 = rng.randint(0, SIZE - h)
        x[i, cls, y0:y0 + h, x0:x0 + w] = 0.9
        gt[i] = [cls + 1, x0, y0, x0 + w, y0 + h]   # labels are 1-based
        for r in range(R):
            if r < R // 2:                          # jittered foreground
                jx = rng.randint(-3, 4)
                jy = rng.randint(-3, 4)
                rois[i, r] = [np.clip(x0 + jx, 0, SIZE - 2),
                              np.clip(y0 + jy, 0, SIZE - 2),
                              np.clip(x0 + w + jx, 1, SIZE - 1),
                              np.clip(y0 + h + jy, 1, SIZE - 1)]
            else:                                   # random background
                bw = rng.randint(8, 24)
                bh = rng.randint(8, 24)
                bx = rng.randint(0, SIZE - bw)
                by = rng.randint(0, SIZE - bh)
                rois[i, r] = [bx, by, bx + bw, by + bh]
    return x, rois, gt


def register_proposal_target(mx):
    """CustomOp assigning each ROI its training label by IoU with the gt
    box (the reference rcnn's proposal_target python op, rcnn/rcnn/symbol
    custom ops)."""

    class ProposalTarget(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            rois = in_data[0].asnumpy()    # (N, R, 4)
            gt = in_data[1].asnumpy()      # (N, 5)
            n, r, _ = rois.shape
            labels = np.zeros((n, r), np.float32)
            for i in range(n):
                g = gt[i, 1:]
                ix0 = np.maximum(rois[i, :, 0], g[0])
                iy0 = np.maximum(rois[i, :, 1], g[1])
                ix1 = np.minimum(rois[i, :, 2], g[2])
                iy1 = np.minimum(rois[i, :, 3], g[3])
                inter = np.clip(ix1 - ix0, 0, None) * \
                    np.clip(iy1 - iy0, 0, None)
                area_r = (rois[i, :, 2] - rois[i, :, 0]) * \
                    (rois[i, :, 3] - rois[i, :, 1])
                area_g = (g[2] - g[0]) * (g[3] - g[1])
                iou = inter / np.maximum(area_r + area_g - inter, 1e-9)
                labels[i] = np.where(iou > 0.5, gt[i, 0], 0.0)
            self.assign(out_data[0], req[0], mx.nd.array(labels))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            for k in range(2):
                self.assign(in_grad[k], req[k],
                            mx.nd.zeros(in_data[k].shape))

    @mx.operator.register("proposal_target")
    class ProposalTargetProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ["rois", "gt"]

        def list_outputs(self):
            return ["label"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0][:2]], []

        def create_operator(self, ctx, shapes, dtypes):
            return ProposalTarget()

    return ProposalTargetProp


def build_net(mx):
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")          # (N, R, 4) pixel corners
    gt = mx.sym.Variable("gt")              # (N, 5)

    body = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                              num_filter=16, name="c1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    body = mx.sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                              num_filter=32, name="c2")
    body = mx.sym.Activation(body, act_type="relu")
    feat = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")                  # stride 4

    # (N, R, 4) -> (N*R, 5): prepend the batch-index column ROIPooling
    # expects (fed as an input since N is a bind-time constant)
    flat = mx.sym.reshape(rois, (-1, 4))    # (N*R, 4)
    bidx = mx.sym.reshape(mx.sym.Variable("roi_batch_idx"), (-1, 1))
    pooled = mx.sym.ROIPooling(feat, mx.sym.Concat(bidx, flat, dim=1),
        pooled_size=(4, 4), spatial_scale=0.25, name="roipool")
    h = mx.sym.Flatten(pooled)
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    cls = mx.sym.FullyConnected(h, num_hidden=NUM_CLASSES + 1, name="cls")

    label = mx.sym.Custom(rois, gt, op_type="proposal_target")
    label = mx.sym.reshape(label, (-1,))    # (N*R,)
    return mx.sym.SoftmaxOutput(cls, label, normalization="valid",
                                name="softmax")


def main():
    parser = argparse.ArgumentParser(description="Fast R-CNN-style demo")
    parser.add_argument("--num-epochs", type=int, default=25)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--num-examples", type=int, default=200)
    parser.add_argument("--min-acc", type=float, default=0.85)
    args = parser.parse_args()

    import mxnet_tpu as mx
    register_proposal_target(mx)
    x, rois, gt = synth_rois(args.num_examples, seed=9)
    B = args.batch_size
    bidx = np.repeat(np.arange(B, dtype=np.float32), R).reshape(B, R, 1)

    sym = build_net(mx)
    mod = mx.mod.Module(sym, context=mx.context.current_context(),
                        data_names=("data", "rois", "roi_batch_idx"),
                        label_names=("gt",))
    mod.bind(data_shapes=[("data", (B, 3, SIZE, SIZE)),
                          ("rois", (B, R, 4)),
                          ("roi_batch_idx", (B, R, 1))],
             label_shapes=[("gt", (B, 5))])
    mod.init_params(mx.init.Xavier(magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    n = (len(x) // B) * B
    for epoch in range(args.num_epochs):
        perm = np.random.RandomState(epoch).permutation(n)
        for s in range(0, n, B):
            idx = perm[s:s + B]
            batch = mx.io.DataBatch(
                data=[mx.nd.array(x[idx]), mx.nd.array(rois[idx]),
                      mx.nd.array(bidx)],
                label=[mx.nd.array(gt[idx])])
            mod.forward_backward(batch)
            mod.update()
        print("epoch %d done" % epoch)

    # evaluate ROI classification on the training set
    correct = total = 0
    for s in range(0, n, B):
        sl = slice(s, s + B)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(x[sl]), mx.nd.array(rois[sl]),
                  mx.nd.array(bidx)],
            label=[mx.nd.array(gt[sl])])
        mod.forward(batch, is_train=False)
        probs = mod.get_outputs()[0].asnumpy()       # (B*R, C+1)
        # oracle labels, same rule as the CustomOp
        import mxnet_tpu as _mx
        lab = _mx.nd.Custom(_mx.nd.array(rois[sl]), _mx.nd.array(gt[sl]),
                            op_type="proposal_target").asnumpy().ravel()
        correct += int((probs.argmax(1) == lab).sum())
        total += lab.size
    acc = correct / total
    print("final ROI classification accuracy: %.4f" % acc)
    assert args.min_acc <= 0 or acc > args.min_acc, "failed to learn ROIs"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Train a multi-scale SSD detector on generated box data, end to end.

Capability twin of the reference's ``example/ssd`` stack
(symbol_builder.py multi-layer heads + MultiBox{Prior,Target,Detection}
contrib ops + train/train_net.py), shrunk to a synthetic dataset: 64x64
images of colored rectangles on noise, 3 classes by color. The network is
the real SSD shape — shared backbone, per-scale conv cls/loc heads,
per-scale anchor priors, concatenated into one MultiBoxTarget during
training and one MultiBoxDetection at inference — and the script asserts
detection quality (mean IoU of the top detection vs ground truth).

Run:  python examples/train_ssd.py --num-epochs 30
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_CLASSES = 3                       # red / green / blue rectangles


def synth_detection(n=400, size=64, seed=0):
    """Images with one axis-aligned colored rectangle each; label rows are
    [cls, xmin, ymin, xmax, ymax] in normalized corners."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 3, size, size).astype(np.float32) * 0.25
    labels = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        cls = rng.randint(0, NUM_CLASSES)
        w = rng.randint(size // 4, size // 2)
        h = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        x[i, cls, y0:y0 + h, x0:x0 + w] = 0.9
        labels[i, 0] = [cls, x0 / size, y0 / size,
                        (x0 + w) / size, (y0 + h) / size]
    return x, labels


def _scale_head(feat, num_anchors, sizes, ratios, name):
    """Per-scale SSD head: cls conv, loc conv, anchor prior (reference:
    example/ssd/symbol/common.py multibox_layer)."""
    import mxnet_tpu as mx
    cls = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                             num_filter=(NUM_CLASSES + 1) * num_anchors,
                             name="%s_cls" % name)
    loc = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                             num_filter=4 * num_anchors,
                             name="%s_loc" % name)
    anchors = mx.sym.MultiBoxPrior(feat, sizes=sizes, ratios=ratios,
                                   clip=True)
    # (N,(C+1)A,H,W) -> (N, cells*A, C+1); (N,4A,H,W) -> (N, cells*A*4)
    cls = mx.sym.reshape(mx.sym.transpose(cls, axes=(0, 2, 3, 1)),
                         shape=(0, -1, NUM_CLASSES + 1))
    loc = mx.sym.reshape(mx.sym.transpose(loc, axes=(0, 2, 3, 1)),
                         shape=(0, -1))
    return cls, loc, anchors


def build_ssd(for_training=True):
    """Two-scale SSD over a small conv backbone."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")

    body = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                              num_filter=16, name="c1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")                       # 32x32
    body = mx.sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                              num_filter=32, name="c2")
    body = mx.sym.Activation(body, act_type="relu")
    feat1 = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")                      # 16x16
    body = mx.sym.Convolution(feat1, kernel=(3, 3), pad=(1, 1),
                              num_filter=32, name="c3")
    body = mx.sym.Activation(body, act_type="relu")
    feat2 = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")                      # 8x8

    # scale 1 catches small boxes, scale 2 large ones;
    # anchors/cell A = len(sizes) + len(ratios) - 1
    cls1, loc1, a1 = _scale_head(feat1, 4, (0.25, 0.35),
                                 (1.0, 0.7, 1.4), "s1")
    cls2, loc2, a2 = _scale_head(feat2, 4, (0.45, 0.6),
                                 (1.0, 0.7, 1.4), "s2")
    cls_pred = mx.sym.Concat(cls1, cls2, dim=1)      # (N, total, C+1)
    loc_pred = mx.sym.Concat(loc1, loc2, dim=1)      # (N, total*4)
    anchors = mx.sym.Concat(a1, a2, dim=1)           # (1, total, 4)
    cls_pred = mx.sym.transpose(cls_pred, axes=(0, 2, 1))  # (N, C+1, total)

    if not for_training:
        cls_prob = mx.sym.SoftmaxActivation(cls_pred, mode="channel")
        det = mx.sym.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       nms_threshold=0.5, force_suppress=True,
                                       nms_topk=50, name="detection")
        return det

    label = mx.sym.Variable("label")
    box_t, box_m, cls_t = mx.sym.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5,
        negative_mining_ratio=3.0, name="target")
    cls_loss = mx.sym.SoftmaxOutput(cls_pred, cls_t, multi_output=True,
                                    use_ignore=True, ignore_label=-1,
                                    normalization="valid", name="cls_prob")
    loc_diff = (loc_pred - box_t) * box_m
    # normalization="valid" divides the loc gradient by the count of live
    # offsets, matching the cls head's 'valid' scale — without it the loc
    # gradient is ~3 orders of magnitude stronger and cls collapses to
    # background (reference SSD uses normalization='valid_thresh' for the
    # same reason, example/ssd/symbol/symbol_builder.py)
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               grad_scale=1.0, normalization="valid",
                               name="loc_loss")
    return mx.sym.Group([cls_loss, loc_loss])


def evaluate(mod_params, x, labels, batch_size):
    """Mean IoU between each image's best detection and its ground-truth
    box (reference: example/ssd/evaluate.py MApMetric in spirit)."""
    import mxnet_tpu as mx
    det_sym = build_ssd(for_training=False)
    det_mod = mx.mod.Module(det_sym, context=mx.context.current_context(),
                            data_names=("data",), label_names=())
    det_mod.bind(data_shapes=[("data", (batch_size, 3, 64, 64))],
                 for_training=False)
    det_mod.set_params(*mod_params, allow_missing=False)
    ious, hits = [], 0
    n = (len(x) // batch_size) * batch_size
    for s in range(0, n, batch_size):
        batch = mx.io.DataBatch(data=[mx.nd.array(x[s:s + batch_size])])
        det_mod.forward(batch, is_train=False)
        out = det_mod.get_outputs()[0].asnumpy()  # (N, topk, 6)
        for i in range(batch_size):
            gt = labels[s + i, 0]
            valid = out[i][out[i, :, 0] >= 0]
            if not len(valid):
                ious.append(0.0)
                continue
            best = valid[np.argmax(valid[:, 1])]  # highest score
            ix0 = max(best[2], gt[1]); iy0 = max(best[3], gt[2])
            ix1 = min(best[4], gt[3]); iy1 = min(best[5], gt[4])
            inter = max(0.0, ix1 - ix0) * max(0.0, iy1 - iy0)
            a1 = (best[4] - best[2]) * (best[5] - best[3])
            a2 = (gt[3] - gt[1]) * (gt[4] - gt[2])
            iou = inter / max(a1 + a2 - inter, 1e-9)
            ious.append(iou)
            hits += int(best[0] == gt[0] and iou > 0.4)
    return float(np.mean(ious)), hits / max(len(ious), 1)


def main():
    parser = argparse.ArgumentParser(description="train a synthetic SSD")
    parser.add_argument("--num-epochs", type=int, default=30)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--num-examples", type=int, default=400)
    parser.add_argument("--min-iou", type=float, default=0.4,
                        help="fail below this mean IoU (<=0 disables)")
    parser.add_argument("--seed", type=int, default=42,
                        help="seeds the mx.random chain the initializer "
                             "draws from (deterministic convergence gate)")
    args = parser.parse_args()

    import mxnet_tpu as mx
    # deterministic init + shuffle: the unseeded global np.random made
    # this convergence gate flaky (CHANGES PR 4/10); the initializer now
    # draws from the seeded mx.random key chain
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    x, labels = synth_detection(args.num_examples, seed=5)
    train = mx.io.NDArrayIter({"data": x}, {"label": labels},
                              args.batch_size, shuffle=True)

    sym = build_ssd(for_training=True)
    mod = mx.mod.Module(sym, context=mx.context.current_context(),
                        data_names=("data",), label_names=("label",))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier(magnitude=2).set_rng(
        mx.random.derive_numpy_rng("train_ssd")))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9, "wd": 1e-4})
    metric = mx.metric.create("loss")
    for epoch in range(args.num_epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
        print("epoch %d done" % epoch)

    miou, acc = evaluate(mod.get_params(), x, labels, args.batch_size)
    print("mean IoU of best detection: %.3f   cls-hit rate: %.3f"
          % (miou, acc))
    assert args.min_iou <= 0 or miou > args.min_iou, \
        "detector failed to localize the boxes"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Deep Q-Network on cart-pole: replay buffer + target network.

Capability twin of the reference's
``example/reinforcement-learning/dqn``: off-policy Q-learning with the
three DQN ingredients — an experience replay buffer sampled uniformly,
a frozen target network synced every N steps, and epsilon-greedy
exploration with decay. The environment is the same self-contained
cart-pole physics used by ``actor_critic.py`` (no gym egress).

Gate: mean evaluation episode length over the last greedy rollouts must
beat the random policy by >2.5x.

Run:  python examples/dqn.py --num-episodes 100
"""
import argparse
import collections
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


class CartPole(object):
    """Classic cart-pole dynamics (Barto-Sutton-Anderson constants)."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        f = 10.0 if action == 1 else -10.0
        ct, st = np.cos(th), np.sin(th)
        tmp = (f + 0.05 * thd * thd * st) / 1.1
        tha = (9.8 * st - ct * tmp) / (0.5 * (4.0 / 3 - 0.1 * ct * ct / 1.1))
        xa = tmp - 0.05 * tha * ct / 1.1
        self.s = np.array([x + 0.02 * xd, xd + 0.02 * xa,
                           th + 0.02 * thd, thd + 0.02 * tha], np.float32)
        done = bool(abs(self.s[0]) > 2.4 or abs(self.s[2]) > 0.21)
        return self.s.copy(), (0.0 if done else 1.0), done


def rollout_greedy(env, qfn, max_steps=300):
    s = env.reset()
    for t in range(max_steps):
        a = int(np.argmax(qfn(s)))
        s, r, done = env.step(a)
        if done:
            return t + 1
    return max_steps


def main():
    p = argparse.ArgumentParser(description="DQN cart-pole")
    p.add_argument("--num-episodes", type=int, default=100)
    p.add_argument("--buffer", type=int, default=10000)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--gamma", type=float, default=0.99)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--target-sync", type=int, default=200,
                   help="steps between target-network syncs")
    p.add_argument("--seed", type=int, default=3)
    args = p.parse_args()
    np.random.seed(args.seed)
    random.seed(args.seed)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    def make_net():
        net = nn.Sequential()
        net.add(nn.Dense(64, activation="tanh"),
                nn.Dense(64, activation="tanh"), nn.Dense(2))
        return net

    qnet, target = make_net(), make_net()
    qnet.initialize(mx.init.Xavier())
    target.initialize(mx.init.Xavier())
    # materialize deferred-init params before the first sync
    dummy = mx.nd.array(np.zeros((1, 4), np.float32))
    qnet(dummy)
    target(dummy)

    def sync_target():
        # gluon's global instance counters give the two nets different
        # prefixes (dense0../dense3..); pair parameters positionally
        src = list(qnet.collect_params().values())
        dst = list(target.collect_params().values())
        for sp, dp in zip(src, dst):
            dp.set_data(sp.data())

    sync_target()
    trainer = gluon.Trainer(qnet.collect_params(), "adam",
                            {"learning_rate": args.lr})
    buf = collections.deque(maxlen=args.buffer)
    env = CartPole(seed=args.seed)
    qfn = lambda s: qnet(mx.nd.array(s[None])).asnumpy()[0]

    baseline = np.mean([rollout_greedy(env, lambda s: np.random.rand(2))
                        for _ in range(20)])
    eps, steps = 1.0, 0
    lengths = []
    for ep in range(args.num_episodes):
        s = env.reset()
        for t in range(200):
            eps = max(0.05, eps * 0.999)
            a = random.randrange(2) if random.random() < eps \
                else int(np.argmax(qfn(s)))
            s2, r, done = env.step(a)
            buf.append((s, a, r, s2, done))
            s = s2
            steps += 1
            if len(buf) >= args.batch_size and steps % 4 == 0:
                batch = random.sample(buf, args.batch_size)
                bs = mx.nd.array(np.stack([b[0] for b in batch]))
                ba = np.array([b[1] for b in batch], np.int64)
                br = np.array([b[2] for b in batch], np.float32)
                bs2 = mx.nd.array(np.stack([b[3] for b in batch]))
                bd = np.array([b[4] for b in batch], np.float32)
                # frozen-target bootstrap: max_a' Q_target(s', a')
                q2 = target(bs2).asnumpy().max(axis=1)
                y = mx.nd.array(br + args.gamma * q2 * (1 - bd))
                with mx.autograd.record():
                    q = qnet(bs)
                    qa = mx.nd.pick(q, mx.nd.array(ba), axis=1)
                    loss = mx.nd.mean(mx.nd.square(qa - y))
                loss.backward()
                trainer.step(1)
            if steps % args.target_sync == 0:
                sync_target()
            if done:
                break
        lengths.append(t + 1)
        if (ep + 1) % 25 == 0:
            print("Episode[%d] mean-length(last 25)=%.1f eps=%.2f"
                  % (ep + 1, np.mean(lengths[-25:]), eps), flush=True)

    final = np.mean([rollout_greedy(env, qfn) for _ in range(10)])
    print("greedy eval: %.1f steps (random baseline %.1f)"
          % (final, baseline))
    assert final > 2.5 * baseline, "DQN did not learn to balance"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Gluon imperative training on the synthetic digit set.

Capability twin of the reference's ``example/gluon/mnist.py``: a
``nn.Sequential`` net trained with ``autograd.record`` + ``gluon.Trainer``,
with ``--hybridize`` compiling the forward into one jitted XLA program
(the HybridBlock/CachedOp path, reference gluon/block.py:283).

Run:  python examples/gluon_mnist.py --num-epochs 3 --hybridize
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from train_mnist import synth_mnist


def build_net():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Flatten())
        net.add(nn.Dense(128, activation="relu"))
        net.add(nn.Dense(64, activation="relu"))
        net.add(nn.Dense(10))
    return net


def evaluate(net, x, y, batch_size, ctx):
    import mxnet_tpu as mx
    correct = 0
    batch_size = min(batch_size, len(y))
    n = (len(y) // batch_size) * batch_size
    for s in range(0, n, batch_size):
        out = net(mx.nd.array(x[s:s + batch_size], ctx=ctx))
        correct += int((out.asnumpy().argmax(1) ==
                        y[s:s + batch_size]).sum())
    return correct / n


def main():
    parser = argparse.ArgumentParser(description="gluon digit classifier")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--num-examples", type=int, default=2000)
    parser.add_argument("--hybridize", action="store_true")
    args = parser.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    ctx = mx.context.current_context()
    x, y = synth_mnist(args.num_examples, seed=7)
    split = int(0.9 * len(y))

    net = build_net()
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = args.batch_size
    for epoch in range(args.num_epochs):
        perm = np.random.RandomState(epoch).permutation(split)
        tot = 0.0
        for s in range(0, split - bs + 1, bs):
            idx = perm[s:s + bs]
            data = mx.nd.array(x[idx], ctx=ctx)
            label = mx.nd.array(y[idx], ctx=ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(bs)
            tot += float(loss.asnumpy().mean())
        print("epoch %d loss %.4f" % (epoch, tot / max(split // bs, 1)))

    acc = evaluate(net, x[split:], y[split:], bs, ctx)
    print("final validation accuracy: %.4f" % acc)
    assert acc > 0.9, "failed to learn the synthetic digits"
    return 0


if __name__ == "__main__":
    sys.exit(main())

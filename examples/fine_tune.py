"""Fine-tune a checkpointed network on a new task.

Capability twin of the reference's
``example/image-classification/fine-tune.py``: load a trained
checkpoint, chop the network at a feature layer, attach a fresh output
head for the new label space, and train with the backbone initialized
from the checkpoint (``set_params(allow_missing=True)`` + fresh init for
the new head — the reference's get_fine_tune_model flow).

Here: pretrain LeNet-ish features on 4 synthetic "pretraining" classes,
then fine-tune to a 3-class relabeling with the backbone frozen. Gates:
the backbone verifiably carries the checkpoint weights (transfer is not
a silent no-op) and the fine-tuned head learns the new task; the
from-scratch number is printed for comparison.

Run:  python examples/fine_tune.py
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth_shapes(n, num_classes, seed):
    """Class = which quadrant holds a bright blob (num_classes <= 4)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, n)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.3
    for i in range(n):
        qy, qx = divmod(int(y[i]), 2)
        x[i, 0, 14 * qy:14 * qy + 12, 14 * qx:14 * qx + 12] += 0.6
    return x, y.astype(np.float32)


def feature_net():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16, name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=32, name="feat")
    return mx.sym.Activation(net, act_type="relu")


def with_head(features, num_classes, head_name):
    import mxnet_tpu as mx
    out = mx.sym.FullyConnected(features, num_hidden=num_classes,
                                name=head_name)
    return mx.sym.SoftmaxOutput(out, name="softmax")


def train(sym, x, y, epochs, lr, ctx, arg_params=None, batch=50,
          fixed_param_names=None):
    import mxnet_tpu as mx
    it = mx.io.NDArrayIter(x, y, batch, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, context=ctx,
                        fixed_param_names=fixed_param_names)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    if arg_params:
        # backbone from the checkpoint; the new head keeps its fresh init
        # (reference fine-tune.py: allow_missing=True)
        mod.set_params(arg_params, {}, allow_missing=True)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": lr,
                                         "momentum": 0.9})
    for _ in range(epochs):
        it.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
    it.reset()
    return mod, dict(mod.score(it, "acc"))["accuracy"]


def main():
    parser = argparse.ArgumentParser(description="fine-tune demo")
    parser.add_argument("--pretrain-epochs", type=int, default=6)
    parser.add_argument("--tune-epochs", type=int, default=3)
    args = parser.parse_args()

    import mxnet_tpu as mx
    mx.random.seed(42)          # deterministic init across runs
    np.random.seed(42)          # deterministic iterator shuffles
    ctx = mx.context.current_context()

    # 1. pretrain on 4 classes, checkpoint
    feats = feature_net()
    xp, yp = synth_shapes(1000, 4, seed=1)
    pre_mod, pre_acc = train(with_head(feats, 4, "fc_pre"), xp, yp,
                             args.pretrain_epochs, 0.05, ctx)
    tmpdir = tempfile.TemporaryDirectory()
    prefix = os.path.join(tmpdir.name, "pre")
    pre_mod.save_checkpoint(prefix, args.pretrain_epochs)
    print("pretrain accuracy: %.3f" % pre_acc)

    # 2. new 3-class task, tiny training budget
    xt, yt = synth_shapes(150, 3, seed=2)
    _, arg_params, _ = mx.model.load_checkpoint(prefix,
                                                args.pretrain_epochs)
    arg_params = {k: v for k, v in arg_params.items()
                  if not k.startswith("fc_pre")}   # drop the old head
    # freeze the backbone (reference fixed_param_names) so the fresh
    # head's initial gradients can't wreck the pretrained features —
    # without this, head-induced noise sets the backbone back below the
    # from-scratch baseline at this budget
    tuned_mod, tuned_acc = train(with_head(feats, 3, "fc_new"), xt, yt,
                                 args.tune_epochs, 0.05, ctx,
                                 arg_params=arg_params,
                                 fixed_param_names=list(arg_params))
    # the transfer must not be a silent no-op: the frozen backbone still
    # carries the checkpoint weights after training
    got = tuned_mod.get_params()[0]["c1_weight"].asnumpy()
    want = arg_params["c1_weight"].asnumpy()
    assert np.allclose(got, want), "backbone did not transfer from ckpt"
    _, scratch_acc = train(with_head(feats, 3, "fc_new"), xt, yt,
                           args.tune_epochs, 0.05, ctx)
    print("fine-tuned: %.3f   from scratch (same budget): %.3f"
          % (tuned_acc, scratch_acc))
    assert tuned_acc > 0.9, "fine-tuned model failed to learn"
    tmpdir.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())

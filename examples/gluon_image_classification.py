"""Gluon model-zoo training: a zoo network + Trainer + hybridize.

Capability twin of the reference's
``example/gluon/image_classification.py`` (model_zoo net at line 119,
``net.hybridize()`` at 168): picks any model-zoo architecture by name,
trains it on a small synthetic image set with ``gluon.Trainer``, and
asserts it learns. Defaults to mobilenet0_25 at 64x64 to stay quick (squeezenet's
relu-after-final-conv head can start dead on synthetic data);
any zoo name works (resnet18_v1, mobilenet0.25, densenet121, ...).

Run:  python examples/gluon_image_classification.py --model mobilenet0_25
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_CLASSES = 4


def synth_images(n=256, size=64, seed=0):
    """4-class 3-channel textures: class = dominant channel + stripe
    direction."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, NUM_CLASSES, n)
    x = rng.rand(n, 3, size, size).astype(np.float32) * 0.3
    yy, xx = np.mgrid[0:size, 0:size]
    hstripe = ((yy // 8) % 2).astype(np.float32)
    vstripe = ((xx // 8) % 2).astype(np.float32)
    for c in range(NUM_CLASSES):
        idx = y == c
        x[idx, c % 3] += 0.5
        x[idx, (c + 1) % 3] += 0.4 * (hstripe if c < 2 else vstripe)
    return x, y.astype(np.float32)


def main():
    parser = argparse.ArgumentParser(description="gluon zoo classifier")
    parser.add_argument("--model", type=str, default="mobilenet0_25")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--num-examples", type=int, default=256)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--no-hybridize", action="store_true")
    parser.add_argument("--min-acc", type=float, default=0.8,
                        help="fail below this train accuracy (<=0 disables)")
    parser.add_argument("--seed", type=int, default=42,
                        help="seeds the mx.random chain the initializer "
                             "draws from (deterministic convergence gate)")
    args = parser.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo import vision

    # deterministic init: route the Xavier draws through the seeded
    # mx.random key chain (the unseeded global np.random was the flake
    # source in this convergence gate — CHANGES PR 4/10)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    ctx = mx.context.current_context()
    net = vision.get_model(args.model, classes=NUM_CLASSES)
    net.initialize(mx.init.Xavier(magnitude=2).set_rng(
        mx.random.derive_numpy_rng("gluon_image_classification")),
        ctx=ctx)
    if not args.no_hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    x, y = synth_images(args.num_examples, args.image_size, seed=3)
    bs = args.batch_size
    for epoch in range(args.num_epochs):
        perm = np.random.RandomState(epoch).permutation(len(y))
        tot, steps = 0.0, 0
        for s in range(0, len(y) - bs + 1, bs):
            idx = perm[s:s + bs]
            data = mx.nd.array(x[idx], ctx=ctx)
            label = mx.nd.array(y[idx], ctx=ctx)
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(bs)
            tot += float(loss.asnumpy().mean())
            steps += 1
        print("epoch %d loss %.4f" % (epoch, tot / max(steps, 1)))

    correct = 0
    for s in range(0, len(y) - bs + 1, bs):
        out = net(mx.nd.array(x[s:s + bs], ctx=ctx))
        correct += int((out.asnumpy().argmax(1) == y[s:s + bs]).sum())
    acc = correct / ((len(y) // bs) * bs)
    print("final train accuracy: %.4f (%s)" % (acc, args.model))
    assert args.min_acc <= 0 or acc > args.min_acc, "failed to learn"
    return 0


if __name__ == "__main__":
    sys.exit(main())

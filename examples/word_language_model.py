"""Gluon word-level language model: Embedding -> LSTM -> Dense, truncated
BPTT with hidden-state carry.

Capability twin of the reference's ``example/gluon/word_language_model``
(train.py: detach hidden state between BPTT segments, grad clipping,
perplexity). The corpus is a deterministic formal grammar (repeating
k-gram patterns + noise words) so the model's achievable perplexity is
known: a learned LSTM must drive validation perplexity far below the
unigram baseline.

Run:  python examples/word_language_model.py --num-epochs 8
"""
import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB = 20


def synth_corpus(n_tokens=12000, seed=0):
    """Mostly-deterministic token stream: cycles of the pattern
    0,1,2,...,9 with occasional random noise tokens from 10..19."""
    rng = np.random.RandomState(seed)
    toks = []
    while len(toks) < n_tokens:
        toks.extend(range(10))
        if rng.rand() < 0.5:
            toks.append(10 + rng.randint(10))
    return np.asarray(toks[:n_tokens], np.int32)


def batchify(data, batch_size):
    """(T, N) column-major segments (reference: word_language_model
    train.py batchify)."""
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T


def main():
    parser = argparse.ArgumentParser(description="gluon LSTM LM")
    parser.add_argument("--num-epochs", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--bptt", type=int, default=20)
    parser.add_argument("--embed", type=int, default=32)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--lr", type=float, default=1.0)
    parser.add_argument("--clip", type=float, default=0.25)
    args = parser.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn, rnn

    ctx = mx.context.current_context()

    class RNNModel(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(VOCAB, args.embed)
                self.lstm = rnn.LSTM(args.hidden, num_layers=1,
                                     layout="TNC")
                self.decoder = nn.Dense(VOCAB, flatten=False)

        def forward(self, inputs, state):
            emb = self.embed(inputs)                  # (T, N, E)
            out, state = self.lstm(emb, state)        # (T, N, H)
            return self.decoder(out), state

    model = RNNModel()
    model.initialize(mx.init.Xavier(), ctx=ctx)
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    corpus = synth_corpus()
    split = int(0.9 * len(corpus))
    train_data = batchify(corpus[:split], args.batch_size)
    val_data = batchify(corpus[split:], args.batch_size)

    def detach(state):
        return [s.detach() for s in state]

    def run_epoch(data, train):
        state = model.lstm.begin_state(batch_size=args.batch_size, ctx=ctx)
        total, count = 0.0, 0
        for s in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[s:s + args.bptt], ctx=ctx)
            y = mx.nd.array(data[s + 1:s + 1 + args.bptt], ctx=ctx)
            state = detach(state)
            if train:
                with autograd.record():
                    out, state = model(x, state)
                    loss = loss_fn(out.reshape((-1, VOCAB)),
                                   y.reshape((-1,)))
                loss.backward()
                grads = [p.grad(ctx) for p in
                         model.collect_params().values()
                         if p.grad_req != "null"]
                gluon.utils.clip_global_norm(
                    grads, args.clip * args.bptt * args.batch_size)
                trainer.step(args.bptt * args.batch_size)
            else:
                out, state = model(x, state)
                loss = loss_fn(out.reshape((-1, VOCAB)),
                               y.reshape((-1,)))
            total += float(loss.asnumpy().sum())
            count += loss.shape[0] if loss.ndim else 1
        return math.exp(total / count)

    # unigram entropy baseline: what a context-free model could reach
    probs = np.bincount(corpus, minlength=VOCAB) / len(corpus)
    probs = probs[probs > 0]
    unigram_ppl = math.exp(-(probs * np.log(probs)).sum())

    for epoch in range(args.num_epochs):
        ppl = run_epoch(train_data, train=True)
        print("epoch %d train perplexity %.2f" % (epoch, ppl))

    val_ppl = run_epoch(val_data, train=False)
    print("final validation perplexity: %.2f (unigram baseline %.2f)"
          % (val_ppl, unigram_ppl))
    assert val_ppl < 0.6 * unigram_ppl, \
        "LSTM failed to beat the unigram baseline decisively"
    return 0


if __name__ == "__main__":
    sys.exit(main())

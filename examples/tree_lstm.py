"""Child-sum Tree-LSTM over recursive boolean-expression trees.

Capability twin of the reference's ``example/gluon/tree_lstm`` (Tai et
al.): a Tree-LSTM cell composes children bottom-up over tree
structures — the recursive-composition capability sequence models
can't express. The cell walks the tree recursively in Python inside
``autograd.record``; training batches trees by TOPOLOGY (two depth
buckets), the tree-model analogue of the reference's
BucketingModule story — all trees in a bucket share one recursion
trace, so the tape compiles once per bucket and the batch rides it.

The task is self-contained: boolean expression trees over
{AND, OR, NOT, 0, 1} in heap layout; the model must EVALUATE the
expression from structure + tokens, which a bag-of-leaves baseline
cannot do (reported for contrast). NOT negates its left child.

Run:  python examples/tree_lstm.py --num-epochs 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

AND, OR, NOT, LIT0, LIT1 = range(5)
VOCAB = 5


def gen_heap_trees(rng, depth, n):
    """n random expression trees of one topology (full binary, heap
    layout): tokens (n, 2^(depth+1)-1) and evaluated truth values."""
    size = 2 ** (depth + 1) - 1
    first_leaf = 2 ** depth - 1
    toks = np.zeros((n, size), np.int64)
    toks[:, first_leaf:] = rng.randint(LIT0, LIT1 + 1, (n, size - first_leaf))
    toks[:, :first_leaf] = rng.randint(AND, NOT + 1, (n, first_leaf))
    vals = np.zeros((n, size), bool)
    vals[:, first_leaf:] = toks[:, first_leaf:] == LIT1
    for i in range(first_leaf - 1, -1, -1):
        l, r = vals[:, 2 * i + 1], vals[:, 2 * i + 2]
        vals[:, i] = np.where(toks[:, i] == AND, l & r,
                              np.where(toks[:, i] == OR, l | r, ~l))
    return toks, vals[:, 0]


def leaf_majority_baseline(toks, y, depth):
    first_leaf = 2 ** depth - 1
    guess = (toks[:, first_leaf:] == LIT1).mean(axis=1) >= 0.5
    return float((guess == y).mean())


def main():
    p = argparse.ArgumentParser(description="child-sum Tree-LSTM")
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--num-trees", type=int, default=800)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--hidden", type=int, default=48)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    H = args.hidden

    class ChildSumTreeLSTM(gluon.Block):
        """Tai et al. child-sum cell over a heap-batched topology: the
        recursion is structural (Python walks child indices), the data
        axis is the batch of trees sharing that topology."""

        def __init__(self, **kw):
            super(ChildSumTreeLSTM, self).__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(VOCAB, H)
                self.W_iou = nn.Dense(3 * H, use_bias=True)
                self.U_iou = nn.Dense(3 * H, use_bias=False)
                self.W_f = nn.Dense(H, use_bias=True)
                self.U_f = nn.Dense(H, use_bias=False)
                self.out = nn.Dense(2)

        def node(self, toks, i, size):
            x = self.embed(mx.nd.slice_axis(toks, axis=1, begin=i,
                                            end=i + 1))
            x = mx.nd.reshape(x, (0, -1))                  # (B, H)
            kids = [k for k in (2 * i + 1, 2 * i + 2) if k < size]
            states = [self.node(toks, k, size) for k in kids]
            if states:
                h_sum = states[0][0]
                for h, _ in states[1:]:
                    h_sum = h_sum + h
                iou = self.W_iou(x) + self.U_iou(h_sum)
            else:
                iou = self.W_iou(x)
            i_g = mx.nd.sigmoid(mx.nd.slice_axis(iou, axis=1, begin=0,
                                                 end=H))
            o_g = mx.nd.sigmoid(mx.nd.slice_axis(iou, axis=1, begin=H,
                                                 end=2 * H))
            u_g = mx.nd.tanh(mx.nd.slice_axis(iou, axis=1, begin=2 * H,
                                              end=3 * H))
            c_new = i_g * u_g
            if states:
                wfx = self.W_f(x)                # shared across children
                for h_k, c_k in states:
                    f_k = mx.nd.sigmoid(wfx + self.U_f(h_k))
                    c_new = c_new + f_k * c_k
            return o_g * mx.nd.tanh(c_new), c_new

        def forward(self, toks, size):
            h, _ = self.node(toks, 0, size)
            return self.out(h)

    rng = np.random.RandomState(1)
    # two topology buckets (depths 2 and 3), like bucketed batching
    buckets = {}
    for depth in (2, 3):
        X, Y = gen_heap_trees(rng, depth, args.num_trees // 2)
        Xv, Yv = gen_heap_trees(rng, depth, 100)
        buckets[depth] = (X, Y, Xv, Yv)

    net = ChildSumTreeLSTM()
    net.initialize(mx.init.Xavier())
    d0 = 2
    net(mx.nd.array(buckets[d0][0][:2].astype(np.float32)),
        2 ** (d0 + 1) - 1)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = args.batch_size
    for epoch in range(args.num_epochs):
        tot, nb = 0.0, 0
        for depth, (X, Y, _, _) in buckets.items():
            size = 2 ** (depth + 1) - 1
            perm = rng.permutation(len(Y))
            for s in range(0, len(Y), bs):
                idx = perm[s:s + bs]
                xb = mx.nd.array(X[idx].astype(np.float32))
                yb = mx.nd.array(Y[idx].astype(np.float32))
                with mx.autograd.record():
                    logits = net(xb, size)
                    loss = mx.nd.mean(sce(logits, yb))
                loss.backward()
                trainer.step(1)
                tot += float(np.asarray(loss.asnumpy()).ravel()[0])
                nb += 1
        print("Epoch[%d] loss=%.4f" % (epoch, tot / nb), flush=True)

    accs, bases = [], []
    for depth, (_, _, Xv, Yv) in buckets.items():
        size = 2 ** (depth + 1) - 1
        pred = net(mx.nd.array(Xv.astype(np.float32)),
                   size).asnumpy().argmax(axis=1)
        accs.append(float((pred == Yv).mean()))
        bases.append(leaf_majority_baseline(Xv, Yv, depth))
    acc, base = float(np.mean(accs)), float(np.mean(bases))
    print("eval accuracy: %.3f per-depth %s (leaf-majority baseline %.3f)"
          % (acc, ["%.3f" % a for a in accs], base))
    assert acc > 0.85, "Tree-LSTM failed to learn boolean evaluation"
    assert acc > base + 0.05, "no structural advantage over bag-of-leaves"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared RecordIO data plumbing for the image-classification CLIs.

Capability twin of the reference's
``example/image-classification/common/data.py``: the same flag surface
(``--data-train``, ``--data-val``, ``--image-shape``, ``--rgb-mean``,
``--data-nthreads``, aug knobs) feeding ``ImageRecordIter`` (the C++
native pipeline when available), plus a synthetic-data path
(``--benchmark``) mirroring the reference's SyntheticDataIter for
perf runs and CI smoke tests.
"""
import argparse

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data")
    data.add_argument("--data-train", type=str, default=None,
                      help="training RecordIO (.rec)")
    data.add_argument("--data-val", type=str, default=None,
                      help="validation RecordIO (.rec)")
    data.add_argument("--image-shape", type=str, default="3,224,224")
    data.add_argument("--num-classes", type=int, default=1000)
    data.add_argument("--num-examples", type=int, default=1281167,
                      help="examples per epoch — fallback for the lr "
                           "schedule when the iterator cannot report "
                           "its (per-worker) size")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    data.add_argument("--data-nthreads", type=int, default=4)
    data.add_argument("--rand-crop", type=int, default=1)
    data.add_argument("--rand-mirror", type=int, default=1)
    data.add_argument("--benchmark", type=int, default=0,
                      help="use synthetic data (reference SyntheticDataIter)")
    return data


def get_rec_iters(args, kv=None):
    """(train, val) ImageRecordIter pair over the flags; --benchmark
    swaps in deterministic synthetic arrays of the right shape."""
    image_shape = tuple(int(d) for d in args.image_shape.split(","))
    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    if args.benchmark:
        rng = np.random.RandomState(17)
        n = max(args.batch_size * 8, 64)
        x = rng.uniform(0, 1, (n,) + image_shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, (n,)).astype(np.float32)
        train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True,
                                  data_name="data", label_name="softmax_label")
        val = mx.io.NDArrayIter(x[: n // 4], y[: n // 4], args.batch_size,
                                data_name="data", label_name="softmax_label")
        return train, val
    if not args.data_train:
        raise ValueError("pass --data-train (or --benchmark 1)")
    mean = [float(v) for v in args.rgb_mean.split(",")]
    common = dict(
        data_shape=image_shape, batch_size=args.batch_size,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        preprocess_threads=args.data_nthreads,
        num_parts=nworker, part_index=rank)
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, shuffle=True,
        rand_crop=bool(args.rand_crop), rand_mirror=bool(args.rand_mirror),
        **common)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(path_imgrec=args.data_val, **common)
    return train, val

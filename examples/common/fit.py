"""Reusable training-CLI harness.

The capability twin of the reference's
``example/image-classification/common/fit.py:108`` — one function wiring
argparse knobs into kvstore, lr schedule, checkpointing, Speedometer, and
``Module.fit``; every image-classification example script calls into it.
"""
from __future__ import annotations

import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import mxnet_tpu as mx


def add_fit_args(parser):
    """(reference: common/fit.py add_fit_args — same flag names so
    reference training commands carry over)."""
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="mlp")
    train.add_argument("--num-epochs", type=int, default=10)
    train.add_argument("--lr", type=float, default=0.05)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=64)
    train.add_argument("--disp-batches", type=int, default=20,
                       help="Speedometer frequency")
    train.add_argument("--model-prefix", type=str, default=None,
                       help="checkpoint path prefix")
    train.add_argument("--load-epoch", type=int, default=None,
                       help="resume from this checkpoint epoch")
    train.add_argument("--kv-store", type=str, default="local")
    train.add_argument("--gpus", type=str, default=None,
                       help="reference compat: device ids, e.g. '0,1' "
                            "(TPU chips here)")
    train.add_argument("--monitor", type=int, default=0,
                       help="monitor stats every N batches")
    train.add_argument("--top-k", type=int, default=0)
    return train


def _contexts(args):
    n_tpu = mx.num_devices("tpu")
    if args.gpus:
        ids = [int(x) for x in args.gpus.split(",")]
        kind = mx.tpu if n_tpu else mx.cpu
        return [kind(i) for i in ids]
    return [mx.tpu(0)] if n_tpu else [mx.cpu(0)]


def _lr_scheduler(args, steps_per_epoch, kv):
    if not args.lr_step_epochs:
        return args.lr, None
    epochs = [int(e) for e in args.lr_step_epochs.split(",") if e]
    begin = args.load_epoch or 0
    lr = args.lr
    for e in epochs:
        if begin >= e:
            lr *= args.lr_factor
    steps = [steps_per_epoch * max(e - begin, 1) for e in epochs
             if e > begin]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor)


def fit(args, network, data_loader, **kwargs):
    """Train ``network`` on the iterators from ``data_loader(args, kv)``
    (reference: common/fit.py:108 fit)."""
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    kv = mx.kv.create(args.kv_store)
    train, val = data_loader(args, kv)

    devs = _contexts(args)
    # per-worker epoch size: num_data reflects distributed sharding
    # (ImageRecordIter num_parts/part_index); --num-examples is the
    # fallback when the iterator cannot report its size
    n_examples = getattr(train, "num_data", 0) or \
        len(getattr(train, "_offsets", []) or []) or \
        getattr(args, "num_examples", 0)
    epoch_size = max(n_examples // args.batch_size, 1)   # batches per epoch
    lr, lr_sched = _lr_scheduler(args, epoch_size, kv)

    checkpoint = None
    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix:
        checkpoint = mx.callback.do_checkpoint(args.model_prefix)
        if args.load_epoch is not None:
            network, arg_params, aux_params = mx.model.load_checkpoint(
                args.model_prefix, args.load_epoch)
            begin_epoch = args.load_epoch

    optimizer_params = {"learning_rate": lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom
    if lr_sched is not None:
        optimizer_params["lr_scheduler"] = lr_sched

    eval_metric = ["accuracy"]
    if args.top_k > 0:
        eval_metric.append(mx.metric.create("top_k_accuracy",
                                            top_k=args.top_k))

    monitor = mx.mon.Monitor(args.monitor, pattern=".*") \
        if args.monitor > 0 else None

    mod = mx.mod.Module(symbol=network, context=devs)
    mod.fit(train, eval_data=val,
            eval_metric=eval_metric,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params, aux_params=aux_params,
            begin_epoch=begin_epoch, num_epoch=args.num_epochs,
            kvstore=kv,
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       args.disp_batches),
            epoch_end_callback=checkpoint,
            monitor=monitor,
            **kwargs)
    return mod

"""FGSM adversarial examples: attack a trained classifier via input
gradients.

Capability twin of the reference's ``example/adversary`` (Goodfellow et
al. FGSM): train a small MLP, then compute the loss gradient **with
respect to the input image** and step in its sign direction — accuracy
on the perturbed batch must collapse while the perturbation stays
eps-bounded. Exercises gradient-wrt-input through a *trained* model
(neural_style.py optimizes an input against fixed features; this
attacks a learned decision boundary).

Run:  python examples/adversary_fgsm.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth_digits(n, seed=0):
    """10-class 16x16 'digit' patterns: class = which cell of a 4-row
    template grid is lit, plus noise."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 16, 16).astype(np.float32) * 0.25
    for c in range(10):
        r, co = divmod(c, 4)
        x[y == c, 0, 4 * r:4 * r + 4, 4 * co:4 * co + 4] += 0.65
    return np.clip(x, 0, 1), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser(description="FGSM adversarial attack")
    p.add_argument("--num-epochs", type=int, default=6)
    p.add_argument("--eps", type=float, default=0.3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    X, Y = synth_digits(1500, seed=1)
    Xv, Yv = synth_digits(300, seed=2)

    net = nn.Sequential()
    net.add(nn.Flatten(), nn.Dense(128, activation="relu"),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = 100
    for epoch in range(args.num_epochs):
        for i in range(0, len(Y), bs):
            xb = mx.nd.array(X[i:i + bs])
            yb = mx.nd.array(Y[i:i + bs])
            with mx.autograd.record():
                loss = mx.nd.mean(sce(net(xb), yb))
            loss.backward()
            trainer.step(1)

    xv = mx.nd.array(Xv)
    yv = mx.nd.array(Yv)
    clean_acc = float((net(xv).asnumpy().argmax(1) == Yv).mean())

    # FGSM: x_adv = x + eps * sign(dL/dx)
    xv.attach_grad()
    with mx.autograd.record():
        loss = mx.nd.mean(sce(net(xv), yv))
    loss.backward()
    g = xv.grad.asnumpy()
    x_adv = np.clip(Xv + args.eps * np.sign(g), 0, 1)
    adv_acc = float((net(mx.nd.array(x_adv)).asnumpy().argmax(1)
                     == Yv).mean())
    linf = float(np.abs(x_adv - Xv).max())
    print("clean accuracy: %.3f   FGSM(eps=%.2f) accuracy: %.3f   "
          "Linf=%.3f" % (clean_acc, args.eps, adv_acc, linf))
    assert clean_acc > 0.95, "model failed to train"
    assert adv_acc < 0.5 * clean_acc, "attack did not degrade the model"
    assert linf <= args.eps + 1e-6, "perturbation exceeded the budget"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CTC sequence recognition: unsegmented label learning (captcha-style).

Capability twin of the reference's ``example/ctc`` /
``example/warpctc``: a recurrent model reads a rendered digit strip and
is trained with CTCLoss against the UNSEGMENTED label sequence — no
per-frame alignment is given; CTC's forward-backward marginalizes over
alignments (the reference bundles Baidu warp-ctc in CUDA for this; here
``CTCLoss`` lowers to a jax dynamic program, ops/contrib).

Decoding is best-path (greedy) with blank/duplicate collapse; the gate
is full-sequence accuracy on held-out strips.

Run:  python examples/ctc_ocr.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_DIGIT, W_DIGIT, H = 2, 8, 12     # 3 digits, 8 cols each + jitter
N_CLASS = 5                         # digits 0..4; CTC blank = N_CLASS
T_FRAMES = N_DIGIT * W_DIGIT + 6


def render(y, rng):
    """Render a digit sequence into an (H, T) strip with horizontal
    position jitter (so frames don't align to labels). Each digit is a
    solid 2-row bar whose vertical position encodes its class."""
    strip = rng.rand(H, T_FRAMES).astype(np.float32) * 0.2
    pos = 1
    for d in y:
        pos += rng.randint(0, 3)
        r0 = 1 + 2 * int(d)
        strip[r0:r0 + 2, pos:pos + 4] += 0.8
        pos += W_DIGIT - 2
    return np.clip(strip, 0, 1)


def synth(n, seed):
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, N_CLASS, (n, N_DIGIT))
    xs = np.stack([render(y, rng) for y in ys])
    return xs.astype(np.float32), ys.astype(np.float32)


def greedy_decode(probs):
    """(T, B, C+1) frame posteriors -> collapsed sequences (class 0 is
    the CTC blank, warp-ctc convention; classes 1..C map to digits
    0..C-1)."""
    ids = probs.argmax(axis=2)                    # (T, B)
    out = []
    for b in range(ids.shape[1]):
        seq, prev = [], -1
        for t in range(ids.shape[0]):
            k = int(ids[t, b])
            if k != prev and k != 0:
                seq.append(k - 1)
            prev = k
        out.append(seq)
    return out


def main():
    p = argparse.ArgumentParser(description="CTC digit-strip OCR")
    p.add_argument("--num-epochs", type=int, default=150)
    p.add_argument("--num-examples", type=int, default=100)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    X, Y = synth(args.num_examples, seed=1)
    Xv, Yv = synth(200, seed=2)

    class Net(gluon.Block):
        """Conv feature extractor over frames -> per-frame class scores.
        (The reference's ctc examples use LSTM encoders; any per-frame
        encoder works — CTC itself is the capability under test, and a
        conv front-end keeps the eager forward cheap.)"""

        def __init__(self, **kw):
            super(Net, self).__init__(**kw)
            with self.name_scope():
                self.c1 = nn.Conv2D(args.hidden, kernel_size=(H, 5),
                                    padding=(0, 2))
                self.c2 = nn.Conv2D(args.hidden, kernel_size=(1, 5),
                                    padding=(0, 2), activation="relu")
                self.c3 = nn.Conv2D(N_CLASS + 1, kernel_size=(1, 1))

        def forward(self, x):             # x: (B, H, T) strip
            h = mx.nd.expand_dims(x, axis=1)           # (B, 1, H, T)
            h = mx.nd.Activation(self.c1(h), act_type="relu")
            h = self.c3(self.c2(h))                    # (B, C+1, 1, T)
            h = mx.nd.squeeze(h, axis=2)               # (B, C+1, T)
            return mx.nd.transpose(h, axes=(0, 2, 1))  # (B, T, C+1)

    net = Net()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    bs = min(args.num_examples, 100)
    for epoch in range(args.num_epochs):
        tot = 0.0
        for i in range(0, len(X), bs):
            xb = mx.nd.array(X[i:i + bs])
            yb = mx.nd.array(Y[i:i + bs])
            with mx.autograd.record():
                logits = net(xb)                       # (B, T, C+1)
                # CTCLoss wants (T, B, C+1) activations
                act = mx.nd.transpose(logits, axes=(1, 0, 2))
                # warp-ctc label convention: classes 1..C,
                # 0 = blank/padding
                loss = mx.nd.mean(mx.nd.CTCLoss(act, yb + 1))
            loss.backward()
            trainer.step(1)
            tot += float(np.asarray(loss.asnumpy()).ravel()[0])
        print("Epoch[%d] ctc-loss=%.4f" % (epoch, tot / (len(X) / bs)),
              flush=True)

    logits = net(mx.nd.array(Xv)).asnumpy()
    probs = np.transpose(logits, (1, 0, 2))
    dec = greedy_decode(probs)
    ok = sum(1 for d, y in zip(dec, Yv)
             if d == [int(v) for v in y])
    acc = ok / len(Yv)
    print("sequence accuracy: %.3f" % acc)
    assert acc > 0.8, "CTC model failed to learn unsegmented sequences"
    return 0


if __name__ == "__main__":
    sys.exit(main())

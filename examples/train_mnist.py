"""Train LeNet/MLP on a generated MNIST-like dataset, end to end.

The capability twin of the reference's
``example/image-classification/train_mnist.py`` (downloads are disabled in
this environment, so the digits are deterministic synthetic glyphs — each
class is a distinct bar/blob pattern plus noise, learnable to ~100%).

Flows exercised: the common fit harness (kvstore, Speedometer, LR steps,
checkpointing), NDArrayIter or — with ``--use-rec`` — the full
pack-to-RecordIO + ImageRecordIter decode/augment pipeline.

Run:  python examples/train_mnist.py --num-epochs 5 --model-prefix /tmp/le
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import fit as fit_mod


def synth_mnist(n=2000, seed=0):
    """Deterministic 28x28 10-class glyphs: class c = c-th horizontal bar
    + c/10-scaled checkerboard + noise."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.3
    yy, xx = np.mgrid[0:28, 0:28]
    checker = ((yy // 4 + xx // 4) % 2).astype(np.float32)
    for c in range(10):
        idx = y == c
        bar = np.zeros((28, 28), np.float32)
        bar[2 * c:2 * c + 3, :] = 1.0
        x[idx, 0] += bar + 0.1 * c * checker
    return x / x.max(), y.astype(np.float32)


def get_mlp():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def get_lenet():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50)
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = mx.sym.Flatten(p2)
    f1 = mx.sym.FullyConnected(f, num_hidden=500)
    a3 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a3, num_hidden=10)
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def _pack_rec(x, y, path):
    """Pack the synthetic set into .rec so ImageRecordIter's decode
    pipeline is exercised (VERDICT: gate fit on the real pipeline)."""
    import cv2
    from mxnet_tpu import recordio
    rec = recordio.MXRecordIO(path, "w")
    for i in range(x.shape[0]):
        img = (x[i, 0] * 255).astype(np.uint8)
        ok, enc = cv2.imencode(".png", img)
        rec.write(recordio.pack(
            recordio.IRHeader(0, float(y[i]), i, 0), enc.tobytes()))
    rec.close()


def data_loader(args, kv):
    import mxnet_tpu as mx
    x, y = synth_mnist(args.num_examples, seed=7)
    split = int(0.9 * len(y))
    if args.use_rec:
        import atexit
        import shutil
        d = tempfile.mkdtemp()
        atexit.register(shutil.rmtree, d, ignore_errors=True)
        _pack_rec(x[:split], y[:split], os.path.join(d, "train.rec"))
        _pack_rec(x[split:], y[split:], os.path.join(d, "val.rec"))
        train = mx.io.ImageRecordIter(
            path_imgrec=os.path.join(d, "train.rec"),
            data_shape=(1, 28, 28), batch_size=args.batch_size,
            shuffle=True, scale=1.0 / 255)
        val = mx.io.ImageRecordIter(
            path_imgrec=os.path.join(d, "val.rec"),
            data_shape=(1, 28, 28), batch_size=args.batch_size,
            scale=1.0 / 255)
        return train, val
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size,
                            label_name="softmax_label")
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train a digit classifier")
    fit_mod.add_fit_args(parser)
    parser.add_argument("--num-examples", type=int, default=2000)
    parser.add_argument("--use-rec", action="store_true",
                        help="train through the RecordIO image pipeline")
    parser.set_defaults(network="mlp", num_epochs=5, lr=0.1,
                        batch_size=100, disp_batches=10)
    args = parser.parse_args()

    net = get_lenet() if args.network == "lenet" else get_mlp()
    # load once; reuse the val iterator for final scoring (with --use-rec a
    # second load would re-encode and re-pack the whole dataset)
    cache = {}

    def loader(a, kv):
        if "iters" not in cache:
            cache["iters"] = data_loader(a, kv)
        return cache["iters"]

    mod = fit_mod.fit(args, net, loader)

    _, val = cache["iters"]
    val.reset()
    score = mod.score(val, "acc")
    print("final validation accuracy: %.4f" % score[0][1])
    assert score[0][1] > 0.9, "failed to learn the synthetic digits"
    if args.model_prefix:
        print("checkpoints at %s-*.params" % args.model_prefix)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Multi-task learning: one backbone, two loss heads, joint gradients.

Capability twin of the reference's ``example/multi-task``: a shared
conv backbone feeds two SoftmaxOutput heads (digit class and a derived
attribute), the Module binds TWO labels, both losses backpropagate
jointly, and a per-head metric tracks each task. The gate requires both
heads to clear their bars AND the shared features to beat two
single-task models trained with the same total epoch budget split
between them (the multi-task transfer effect on correlated tasks).

Run:  python examples/multi_task.py --num-epochs 5
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth(n, seed=0):
    """Task 1: which grid cell is lit (10-way). Task 2: parity of the
    cell index (2-way) — fully derived, so features transfer."""
    rng = np.random.RandomState(seed)
    y1 = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 16, 16).astype(np.float32) * 0.3
    for c in range(10):
        r, co = divmod(c, 4)
        x[y1 == c, 0, 4 * r:4 * r + 4, 4 * co:4 * co + 4] += 0.55
    return (np.clip(x, 0, 1), y1.astype(np.float32),
            (y1 % 2).astype(np.float32))


def build(heads=("digit", "parity")):
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                           name="c1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = mx.sym.Flatten(h)
    h = mx.sym.FullyConnected(h, num_hidden=64, name="shared")
    h = mx.sym.Activation(h, act_type="tanh")
    outs = []
    if "digit" in heads:
        fc1 = mx.sym.FullyConnected(h, num_hidden=10, name="digit_fc")
        outs.append(mx.sym.SoftmaxOutput(
            fc1, mx.sym.Variable("digit_label"), name="digit"))
    if "parity" in heads:
        fc2 = mx.sym.FullyConnected(h, num_hidden=2, name="parity_fc")
        outs.append(mx.sym.SoftmaxOutput(
            fc2, mx.sym.Variable("parity_label"), name="parity"))
    return mx.sym.Group(outs) if len(outs) > 1 else outs[0]


def train(heads, X, Y1, Y2, args, epochs):
    import mxnet_tpu as mx
    label_shapes = []
    labels = []
    if "digit" in heads:
        label_shapes.append(("digit_label", (args.batch_size,)))
        labels.append(Y1)
    if "parity" in heads:
        label_shapes.append(("parity_label", (args.batch_size,)))
        labels.append(Y2)
    mod = mx.mod.Module(build(heads), context=mx.cpu(0),
                        label_names=[n for n, _ in label_shapes])
    mod.bind(data_shapes=[("data", (args.batch_size, 1, 16, 16))],
             label_shapes=label_shapes)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    it = mx.io.NDArrayIter({"data": X},
                           dict(zip([n for n, _ in label_shapes], labels)),
                           args.batch_size, shuffle=True)
    for _ in range(epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
    return mod


def evaluate(mod, heads, Xv, Y1v, Y2v, args):
    import mxnet_tpu as mx
    accs = {}
    n = (len(Xv) // args.batch_size) * args.batch_size
    outs_all = []
    it = mx.io.NDArrayIter({"data": Xv[:n]}, None, args.batch_size)
    for batch in it:
        mod.forward(batch, is_train=False)
        outs_all.append([o.asnumpy() for o in mod.get_outputs()])
    stacked = [np.concatenate([b[i] for b in outs_all])
               for i in range(len(outs_all[0]))]
    idx = 0
    if "digit" in heads:
        accs["digit"] = float(
            (stacked[idx].argmax(1) == Y1v[:n]).mean())
        idx += 1
    if "parity" in heads:
        accs["parity"] = float(
            (stacked[idx].argmax(1) == Y2v[:n]).mean())
    return accs


def main():
    p = argparse.ArgumentParser(description="two-head multi-task net")
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--num-examples", type=int, default=1200)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    X, Y1, Y2 = synth(args.num_examples, seed=1)
    Xv, Y1v, Y2v = synth(400, seed=2)

    mod = train(("digit", "parity"), X, Y1, Y2, args, args.num_epochs)
    acc = evaluate(mod, ("digit", "parity"), Xv, Y1v, Y2v, args)
    print("multi-task: digit=%.4f parity=%.4f"
          % (acc["digit"], acc["parity"]))
    assert acc["digit"] > 0.9 and acc["parity"] > 0.9, \
        "joint training failed"

    # single-task baselines on a split epoch budget (same total compute)
    half = max(args.num_epochs // 2, 1)
    m1 = train(("digit",), X, Y1, Y2, args, half)
    a1 = evaluate(m1, ("digit",), Xv, Y1v, Y2v, args)["digit"]
    m2 = train(("parity",), X, Y1, Y2, args, half)
    a2 = evaluate(m2, ("parity",), Xv, Y1v, Y2v, args)["parity"]
    print("single-task split budget: digit=%.4f parity=%.4f" % (a1, a2))
    assert acc["digit"] + acc["parity"] >= a1 + a2 - 0.02, \
        "multi-task gave no transfer benefit at equal budget"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Gluon actor-critic on CartPole — the RL breadth example.

Capability twin of the reference's ``example/gluon/actor_critic.py``
(policy+value net, REINFORCE-with-baseline updates from episode returns).
The gym dependency is replaced by an inline CartPole physics step (the
standard cart-pole ODE with Euler integration), so the example is fully
self-contained; the gate is the mean episode length growing well past
the random-policy baseline.

Run:  python examples/actor_critic.py --num-episodes 150
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


class CartPole(object):
    """Classic cart-pole balance task (standard dynamics constants)."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)
        self.g, self.mc, self.mp, self.l = 9.8, 1.0, 0.1, 0.5
        self.force, self.dt = 10.0, 0.02
        self.x_lim, self.th_lim = 2.4, 12 * np.pi / 180

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        f = self.force if action == 1 else -self.force
        costh, sinth = np.cos(th), np.sin(th)
        m = self.mc + self.mp
        temp = (f + self.mp * self.l * thd ** 2 * sinth) / m
        thacc = (self.g * sinth - costh * temp) / \
            (self.l * (4.0 / 3.0 - self.mp * costh ** 2 / m))
        xacc = temp - self.mp * self.l * thacc * costh / m
        x, xd = x + self.dt * xd, xd + self.dt * xacc
        th, thd = th + self.dt * thd, thd + self.dt * thacc
        self.s = np.array([x, xd, th, thd], np.float32)
        done = abs(x) > self.x_lim or abs(th) > self.th_lim
        return self.s.copy(), 1.0, done


def main():
    p = argparse.ArgumentParser(description="actor-critic cart-pole")
    p.add_argument("--num-episodes", type=int, default=150)
    p.add_argument("--max-steps", type=int, default=200)
    p.add_argument("--gamma", type=float, default=0.99)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, Trainer
    np.random.seed(args.seed)   # initializers draw from the global RNG

    class ActorCritic(nn.HybridSequential):
        """Shared body; policy logits + value head (reference
        actor_critic.py Net)."""

        def __init__(self):
            super().__init__()
            # Block.__setattr__ auto-registers Block-valued attributes
            self.body = nn.Dense(64, activation="relu", in_units=4)
            self.policy = nn.Dense(2, in_units=64)
            self.value = nn.Dense(1, in_units=64)

        def forward(self, x):
            h = self.body(x)
            return self.policy(h), self.value(h)

    net = ActorCritic()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})
    env = CartPole(args.seed)
    rng = np.random.RandomState(args.seed + 1)

    lengths = []
    for ep in range(args.num_episodes):
        s = env.reset()
        states, actions, rewards = [], [], []
        for _ in range(args.max_steps):
            logits, _ = net(mx.nd.array(s[None]))
            z = logits.asnumpy()[0]
            probs = np.exp(z - z.max())    # stabilized softmax
            probs /= probs.sum()
            a = int(rng.rand() < probs[1])
            s2, r, done = env.step(a)
            states.append(s)
            actions.append(a)
            rewards.append(r)
            s = s2
            if done:
                break
        lengths.append(len(rewards))

        # discounted returns, normalized
        R, rets = 0.0, []
        for r in reversed(rewards):
            R = r + args.gamma * R
            rets.append(R)
        rets = np.asarray(rets[::-1], np.float32)
        rets = (rets - rets.mean()) / (rets.std() + 1e-6)

        xs = mx.nd.array(np.stack(states))
        acts = np.asarray(actions)
        retnd = mx.nd.array(rets)
        with mx.autograd.record():
            logits, values = net(xs)
            logp = mx.nd.log_softmax(logits, axis=-1)
            chosen = mx.nd.pick(logp, mx.nd.array(
                acts.astype(np.float32)), axis=1)
            values = mx.nd.reshape(values, (-1,))
            adv = retnd - values
            # policy gradient with the critic baseline + value regression
            actor = -mx.nd.mean(chosen * mx.nd.stop_gradient(adv))
            critic = mx.nd.mean(mx.nd.square(adv))
            loss = actor + 0.5 * critic
        loss.backward()
        trainer.step(1)
        if (ep + 1) % 25 == 0:
            print("Episode[%d] mean-len(last 25)=%.1f"
                  % (ep + 1, np.mean(lengths[-25:])), flush=True)

    first = np.mean(lengths[:25])
    last = np.mean(lengths[-25:])
    print("mean episode length: first25=%.1f last25=%.1f" % (first, last))
    assert last > first * 1.5, "actor-critic did not improve"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Gluon super-resolution: sub-pixel (pixel-shuffle) upscaling CNN.

Capability twin of the reference's ``example/gluon/super_resolution.py``
(ESPCN, Shi et al.: conv stack -> Conv2D(upscale^2 channels) ->
pixel-shuffle reorder -> upscaled image, L2 loss). The dataset is
synthetic band-limited imagery (random low-frequency mixtures), so the
2x upscaling task has a known-learnable structure and PSNR against
bicubic-style baseline interpolation is a real gate.

Run:  python examples/super_resolution.py --num-epochs 14
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_images(n, hw, seed=0):
    """Band-limited images: sums of low-frequency sinusoid products."""
    rng = np.random.RandomState(seed)
    yy, xx = np.meshgrid(np.linspace(0, 1, hw), np.linspace(0, 1, hw),
                         indexing="ij")
    imgs = np.zeros((n, 1, hw, hw), np.float32)
    for i in range(n):
        img = np.zeros((hw, hw), np.float32)
        for _ in range(4):
            fx, fy = rng.uniform(0.5, 3.0, 2)
            ph = rng.uniform(0, 2 * np.pi, 2)
            img += rng.uniform(0.3, 1.0) * np.sin(
                2 * np.pi * fx * xx + ph[0]) * np.sin(
                2 * np.pi * fy * yy + ph[1])
        img = (img - img.min()) / (img.max() - img.min() + 1e-6)
        imgs[i, 0] = img
    return imgs


def downscale(imgs, factor):
    """Box-average downscale (the LR inputs)."""
    n, c, h, w = imgs.shape
    return imgs.reshape(n, c, h // factor, factor,
                        w // factor, factor).mean((3, 5))


def nearest_upscale(imgs, factor):
    return imgs.repeat(factor, axis=2).repeat(factor, axis=3)


def psnr(a, b):
    mse = float(np.mean((a - b) ** 2))
    return 10.0 * math.log10(1.0 / max(mse, 1e-12))


class SuperResolutionNet:
    """conv3x3(64) -> conv3x3(64) -> conv3x3(32) -> conv3x3(r^2) ->
    pixel shuffle (reference ESPCN layout)."""

    def __init__(self, mx, upscale):
        from mxnet_tpu.gluon import nn
        self.upscale = upscale
        net = nn.HybridSequential()
        net.add(nn.Conv2D(64, kernel_size=5, padding=2, activation="relu"))
        net.add(nn.Conv2D(64, kernel_size=3, padding=1, activation="relu"))
        net.add(nn.Conv2D(32, kernel_size=3, padding=1, activation="relu"))
        net.add(nn.Conv2D(upscale * upscale, kernel_size=3, padding=1))
        self.body = net

    def __call__(self, x):
        import mxnet_tpu as mx
        r = self.upscale
        y = self.body(x)                             # (N, r*r, H, W)
        n, _, h, w = y.shape
        # pixel shuffle: (N, r*r, H, W) -> (N, 1, H*r, W*r)
        y = mx.nd.reshape(y, (n, r, r, h, w))
        y = mx.nd.transpose(y, axes=(0, 3, 1, 4, 2))  # (N, H, r, W, r)
        y = mx.nd.reshape(y, (n, 1, h * r, w * r))
        # global residual (VDSR-style): predict the correction on top of
        # nearest upscaling, so training starts at the baseline PSNR
        near = mx.nd.repeat(mx.nd.repeat(x, repeats=r, axis=2),
                            repeats=r, axis=3)
        return y + near

    def collect_params(self):
        return self.body.collect_params()

    def initialize(self, init):
        self.body.initialize(init)


def main():
    p = argparse.ArgumentParser(description="ESPCN super resolution")
    p.add_argument("--num-epochs", type=int, default=14)
    p.add_argument("--num-examples", type=int, default=96)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--upscale", type=int, default=2)
    p.add_argument("--hw", type=int, default=32, help="high-res size")
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import Trainer
    np.random.seed(args.seed)   # initializers draw from the global RNG

    hi = make_images(args.num_examples, args.hw)
    lo = downscale(hi, args.upscale)
    n_val = max(args.batch_size, args.num_examples // 6)
    tr_lo, tr_hi = lo[n_val:], hi[n_val:]
    va_lo, va_hi = lo[:n_val], hi[:n_val]

    net = SuperResolutionNet(mx, args.upscale)
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})

    nb = len(tr_lo) // args.batch_size
    if nb < 1:
        p.error("--num-examples %d leaves %d training images after the "
                "validation split; need at least one batch of %d"
                % (args.num_examples, len(tr_lo), args.batch_size))
    for epoch in range(args.num_epochs):
        tic = time.time()
        tot = 0.0
        for b in range(nb):
            x = mx.nd.array(tr_lo[b * args.batch_size:
                                  (b + 1) * args.batch_size])
            y = mx.nd.array(tr_hi[b * args.batch_size:
                                  (b + 1) * args.batch_size])
            with mx.autograd.record():
                out = net(x)
                loss = mx.nd.mean(mx.nd.square(out - y))
            loss.backward()
            trainer.step(args.batch_size)
            tot += float(loss.asnumpy())
        print("Epoch[%d] mse=%.5f (%.1fs)"
              % (epoch, tot / nb, time.time() - tic), flush=True)

    pred = net(mx.nd.array(va_lo)).asnumpy()
    base = nearest_upscale(va_lo, args.upscale)
    p_net = psnr(pred, va_hi)
    p_base = psnr(base, va_hi)
    print("PSNR: net=%.2f dB baseline(nearest)=%.2f dB" % (p_net, p_base))
    assert p_net > p_base, "super-resolution net did not beat nearest"
    return 0


if __name__ == "__main__":
    sys.exit(main())

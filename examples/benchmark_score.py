"""Inference throughput across the model zoo — the perf-table script.

Capability twin of the reference's
``example/image-classification/benchmark_score.py``, the script that
produced the published inference numbers in docs/how_to/perf.md (e.g.
ResNet-50 batch 32: 713 img/s on P100 — BASELINE.md). Builds each network
as a Symbol, binds a forward-only executor, and reports img/s per
(network, batch size).

Run:  python examples/benchmark_score.py --network resnet-50 --batch-sizes 1,32
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def get_symbol(network):
    import mxnet_tpu as mx
    from mxnet_tpu.models import alexnet, inception, lenet, mlp, resnet, vgg
    if network.startswith("resnet-"):
        return resnet.get_symbol(num_classes=1000,
                                 num_layers=int(network.split("-")[1])), 224
    if network.startswith("vgg-"):
        return vgg.get_symbol(num_classes=1000,
                              num_layers=int(network.split("-")[1])), 224
    if network == "alexnet":
        return alexnet.get_symbol(num_classes=1000), 224
    if network == "inception-v3":
        return inception.get_symbol(num_classes=1000, version="v3"), 299
    if network == "inception-bn":
        return inception.get_symbol(num_classes=1000, version="bn"), 224
    if network == "lenet":
        return lenet.get_symbol(num_classes=10), 28
    raise ValueError("unknown network %r" % network)


def score(network, batch_size, ctx, iters=20, warmup=3, train=False):
    """img/s for one (network, batch) — the reference's score() shape.

    ``train=True`` times the fused fwd+bwd+SGD-update step instead (the
    reference's training table uses train_imagenet.py; same workload)."""
    import mxnet_tpu as mx
    sym, size = get_symbol(network)
    channels = 1 if network == "lenet" else 3
    mod = mx.mod.Module(sym, context=ctx)
    # the loss head keeps a label arg; bind a dummy shape (forward-only
    # softmax ignores it — same situation Predictor zero-fills)
    mod.bind(data_shapes=[("data", (batch_size, channels, size, size))],
             label_shapes=[("softmax_label", (batch_size,))],
             for_training=train)
    mod.init_params(mx.init.Xavier(magnitude=2))
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(
            rng.uniform(-1, 1, (batch_size, channels, size, size))
            .astype(np.float32), ctx=ctx)],
        label=[mx.nd.array(
            rng.randint(0, 1000, (batch_size,)).astype(np.float32),
            ctx=ctx)])

    if train:
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01,
                                             "momentum": 0.9})
        first_param = sorted(mod._exec.arg_dict)[0]

        def run_once():
            mod._fit_step(batch)

        def drain():
            return float(np.asarray(
                mod._exec.arg_dict[first_param].data.ravel()[0]))
    else:
        def run_once():
            mod.forward(batch, is_train=False)

        def drain():
            return float(mod.get_outputs()[0].asnumpy().ravel()[0])

    for _ in range(warmup):
        run_once()
    drain()
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    drain()
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main():
    parser = argparse.ArgumentParser(description="inference perf table")
    parser.add_argument("--network", type=str, default="resnet-50",
                        help="resnet-18/34/50/101/152, vgg-11/16/19, "
                             "alexnet, lenet, or 'all'")
    parser.add_argument("--batch-sizes", type=str, default="1,32")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--bf16", action="store_true",
                        help="mixed-precision inference (mx.amp)")
    parser.add_argument("--train", action="store_true",
                        help="time the fused train step instead of forward")
    args = parser.parse_args()

    import mxnet_tpu as mx
    if args.bf16:
        mx.amp.init("bfloat16")
    ctx = mx.tpu(0) if mx.num_devices("tpu") else mx.cpu(0)
    print("context:", ctx)
    nets = (["alexnet", "vgg-16", "inception-bn", "inception-v3",
             "resnet-50", "resnet-152"]
            if args.network == "all" else [args.network])
    for net in nets:
        for bs in [int(b) for b in args.batch_sizes.split(",")]:
            img_s = score(net, bs, ctx, iters=args.iters, train=args.train)
            print("network: %-12s batch: %-4d  %.1f img/s%s"
                  % (net, bs, img_s, " (train)" if args.train else ""),
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

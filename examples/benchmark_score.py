"""Inference throughput across the model zoo — the perf-table script.

Capability twin of the reference's
``example/image-classification/benchmark_score.py``, the script that
produced the published inference numbers in docs/how_to/perf.md (e.g.
ResNet-50 batch 32: 713 img/s on P100 — BASELINE.md). Builds each network
as a Symbol, binds a forward-only executor, and reports img/s per
(network, batch size).

Run:  python examples/benchmark_score.py --network resnet-50 --batch-sizes 1,32
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def get_symbol(network):
    import mxnet_tpu as mx
    from mxnet_tpu.models import alexnet, lenet, mlp, resnet, vgg
    if network.startswith("resnet-"):
        return resnet.get_symbol(num_classes=1000,
                                 num_layers=int(network.split("-")[1])), 224
    if network.startswith("vgg-"):
        return vgg.get_symbol(num_classes=1000,
                              num_layers=int(network.split("-")[1])), 224
    if network == "alexnet":
        return alexnet.get_symbol(num_classes=1000), 224
    if network == "lenet":
        return lenet.get_symbol(num_classes=10), 28
    raise ValueError("unknown network %r" % network)


def score(network, batch_size, ctx, iters=20, warmup=3):
    """img/s for one (network, batch) — the reference's score() shape."""
    import mxnet_tpu as mx
    sym, size = get_symbol(network)
    channels = 1 if network == "lenet" else 3
    mod = mx.mod.Module(sym, context=ctx)
    # the loss head keeps a label arg; bind a dummy shape (forward-only
    # softmax ignores it — same situation Predictor zero-fills)
    mod.bind(data_shapes=[("data", (batch_size, channels, size, size))],
             label_shapes=[("softmax_label", (batch_size,))],
             for_training=False)
    mod.init_params(mx.init.Xavier(magnitude=2))
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(data=[mx.nd.array(
        rng.uniform(-1, 1, (batch_size, channels, size, size))
        .astype(np.float32), ctx=ctx)])

    def drain():
        return float(mod.get_outputs()[0].asnumpy().ravel()[0])

    for _ in range(warmup):
        mod.forward(batch, is_train=False)
    drain()
    t0 = time.perf_counter()
    for _ in range(iters):
        mod.forward(batch, is_train=False)
    drain()
    dt = time.perf_counter() - t0
    return batch_size * iters / dt


def main():
    parser = argparse.ArgumentParser(description="inference perf table")
    parser.add_argument("--network", type=str, default="resnet-50",
                        help="resnet-18/34/50/101/152, vgg-11/16/19, "
                             "alexnet, lenet, or 'all'")
    parser.add_argument("--batch-sizes", type=str, default="1,32")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--bf16", action="store_true",
                        help="mixed-precision inference (mx.amp)")
    args = parser.parse_args()

    import mxnet_tpu as mx
    if args.bf16:
        mx.amp.init("bfloat16")
    ctx = mx.tpu(0) if mx.num_devices("tpu") else mx.cpu(0)
    print("context:", ctx)
    nets = (["alexnet", "vgg-16", "resnet-50", "resnet-152"]
            if args.network == "all" else [args.network])
    for net in nets:
        for bs in [int(b) for b in args.batch_sizes.split(",")]:
            img_s = score(net, bs, ctx, iters=args.iters)
            print("network: %-12s batch: %-4d  %.1f img/s" % (net, bs, img_s))
    return 0


if __name__ == "__main__":
    sys.exit(main())

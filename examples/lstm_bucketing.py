"""Bucketed LSTM language model on synthetic sequences.

The capability twin of the reference's ``example/rnn/lstm_bucketing.py``
(PTB there; download-disabled environment here, so sequences are drawn
from a learnable deterministic token chain with variable lengths).
Exercises BucketSentenceIter auto-bucketing + BucketingModule compiling
one executor per bucket with shared weights.

Run:  python examples/lstm_bucketing.py --num-epochs 5
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth_sentences(n=600, vocab=30, seed=3):
    """Variable-length sequences where token t+1 = (t*2 + 1) mod vocab with
    occasional noise — a pattern an LSTM learns quickly."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        length = rng.choice([8, 12, 16])
        s = [int(rng.randint(1, vocab))]
        for _ in range(length - 1):
            nxt = (s[-1] * 2 + 1) % vocab or 1
            if rng.rand() < 0.05:
                nxt = int(rng.randint(1, vocab))
            s.append(nxt)
        out.append(s)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--disp-batches", type=int, default=10)
    args = ap.parse_args()

    import mxnet_tpu as mx

    train = mx.rnn.BucketSentenceIter(synth_sentences(), args.batch_size,
                                      invalid_label=0, seed=1)
    val = mx.rnn.BucketSentenceIter(synth_sentences(seed=9),
                                    args.batch_size, invalid_label=0,
                                    seed=2)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        """One unrolled graph per bucket length, weights shared through
        the cell params (reference: lstm_bucketing.py sym_gen)."""
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=args.vocab,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed,
                                       merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab,
                                     name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, use_ignore=True,
                                    ignore_label=0, normalization="valid",
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    ctx = mx.tpu(0) if mx.num_devices("tpu") else mx.cpu(0)
    model = mx.mod.BucketingModule(sym_gen,
                                   default_bucket_key=train.default_bucket_key,
                                   context=ctx)

    import logging
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    metric = mx.metric.Perplexity(ignore_label=0)
    model.fit(train, eval_data=val, eval_metric=metric,
              optimizer="sgd",
              optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
              initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(
                  args.batch_size, args.disp_batches))

    score = model.score(val, metric)
    ppl = score[0][1]
    print("final validation perplexity: %.3f" % ppl)
    # the chain is ~95% deterministic over `vocab` symbols: far below
    # uniform (vocab) means the LSTM learned the transition rule
    assert ppl < args.vocab / 3, "did not learn the chain (ppl %.2f)" % ppl
    return 0


if __name__ == "__main__":
    sys.exit(main())

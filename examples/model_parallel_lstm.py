"""Model-parallel stacked LSTM: each layer pinned to its own device.

The capability twin of the reference's ``example/model-parallel-lstm/
lstm.py:65-129`` (there: each LSTM layer's weights created under
``with mx.AttrScope(ctx_group='layer%d')`` and bound with
``group2ctx={'layer0': gpu(0), ...}``). Here the same ``ctx_group`` /
``group2ctx`` surface places layers across the available devices, and the
executor runs the graph op-by-op with boundary transfers — on a real pod,
pipeline placement across chips with ICI hops.

Run on the CPU rig:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/model_parallel_lstm.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_symbol(mx, num_layers, num_hidden, seq_len, vocab):
    """Stacked LSTM LM with each layer in its own ctx group."""
    with mx.AttrScope(ctx_group="embed"):
        data = mx.sym.Variable("data")                      # (N, T)
        weight = mx.sym.Variable("embed_weight")
        emb = mx.sym.Embedding(data, weight, input_dim=vocab,
                               output_dim=num_hidden, name="embed")
    hidden = mx.sym.SwapAxis(emb, dim1=0, dim2=1)           # (T, N, H)
    stack = []
    for i in range(num_layers):
        with mx.AttrScope(ctx_group="layer%d" % i):
            cell = mx.rnn.LSTMCell(num_hidden, prefix="lstm%d_" % i)
            outs, _ = cell.unroll(seq_len, inputs=hidden, layout="TNC",
                                  merge_outputs=True)
            hidden = outs
            stack.append(cell)
    with mx.AttrScope(ctx_group="head"):
        flat = mx.sym.Reshape(hidden, shape=(-1, num_hidden))
        logits = mx.sym.FullyConnected(flat, num_hidden=vocab, name="cls")
        label = mx.sym.Reshape(mx.sym.SwapAxis(mx.sym.Variable("label"),
                                               dim1=0, dim2=1), shape=(-1,))
        out = mx.sym.SoftmaxOutput(logits, label, normalization="valid",
                                   name="softmax")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=50)
    ap.add_argument("--steps", type=int, default=25)
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # some accelerator plugins rewrite JAX_PLATFORMS at startup; the
        # config override makes the documented CPU-rig invocation stick
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    n_dev = mx.num_devices("tpu") or mx.num_devices("cpu")
    kind = mx.tpu if mx.num_devices("tpu") else mx.cpu
    # embed + layers + head, round-robin over what we have
    groups = ["embed"] + ["layer%d" % i for i in range(args.num_layers)] \
        + ["head"]
    group2ctx = {g: kind(i % n_dev) for i, g in enumerate(groups)}
    print("placement:", {g: str(c) for g, c in group2ctx.items()})

    np.random.seed(7)     # initializers draw from numpy's global RNG
    mx.random.seed(7)
    sym = build_symbol(mx, args.num_layers, args.num_hidden, args.seq_len,
                       args.vocab)
    # explicit init-state shapes, like the reference's init_c/init_h inputs
    state_shapes = {n: (args.batch, args.num_hidden)
                    for n in sym.list_arguments() if "begin_state" in n}
    ex = sym.simple_bind(ctx=kind(0), grad_req="write",
                         group2ctx=group2ctx,
                         data=(args.batch, args.seq_len),
                         label=(args.batch, args.seq_len), **state_shapes)
    init = mx.init.Xavier()
    for name, arr in ex.arg_dict.items():
        if name in ("data", "label"):
            continue
        if "begin_state" in name:
            arr[:] = 0
        else:
            init(name, arr)

    # learnable synthetic LM task: the next token is (current + 1) % vocab
    rng = np.random.RandomState(0)
    x = rng.randint(1, args.vocab, (args.batch, args.seq_len))
    y = ((x + 1) % args.vocab).astype(np.float32)
    x = x.astype(np.float32)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["label"][:] = y

    lr, mom = 5.0, 0.9
    vel = {}
    first = last = None
    for step in range(args.steps):
        out = ex.forward(is_train=True)[0]
        probs = out.asnumpy().reshape(args.seq_len, args.batch, args.vocab)
        flat_label = y.T.reshape(-1).astype(int)
        nll = -np.log(np.maximum(
            probs.reshape(-1, args.vocab)[np.arange(flat_label.size),
                                          flat_label], 1e-12)).mean()
        ex.backward()
        for name, grad in ex.grad_dict.items():
            if name in ("data", "label") or grad is None:
                continue
            v = vel.get(name)
            v = mom * v - lr * grad if v is not None else -lr * grad
            vel[name] = v
            ex.arg_dict[name][:] = ex.arg_dict[name] + v
        if first is None:
            first = nll
        last = nll
        if step % 5 == 0 or step == args.steps - 1:
            print("step %3d  nll %.4f" % (step, nll))
    assert last < first * 0.7, "model-parallel LSTM failed to learn " \
        "(nll %.4f -> %.4f)" % (first, last)
    print("ok: nll %.4f -> %.4f across %d devices" % (first, last, n_dev))


if __name__ == "__main__":
    main()

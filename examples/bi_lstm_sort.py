"""Bi-LSTM sort: learn to emit the sorted version of an int sequence.

Capability twin of the reference's ``example/bi-lstm-sort`` (a
BidirectionalCell LSTM reads the whole sequence, a per-position
projection emits the sorted tokens). Synthetic data over a small vocab;
gate = per-position accuracy far above chance.

Run:  python examples/bi_lstm_sort.py --num-epochs 10
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB, SEQ = 12, 6


def synth(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, VOCAB, (n, SEQ))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def get_symbol(num_hidden=48, num_embed=16):
    import mxnet_tpu as mx
    from mxnet_tpu.rnn import LSTMCell, BidirectionalCell

    data = mx.sym.Variable("data")                     # (N, SEQ)
    embed = mx.sym.Embedding(data, mx.sym.Variable("embed_weight"),
                             input_dim=VOCAB, output_dim=num_embed,
                             name="embed")             # (N, SEQ, E)
    bi = BidirectionalCell(LSTMCell(num_hidden, prefix="fw_"),
                           LSTMCell(num_hidden, prefix="bw_"),
                           output_prefix="bi_")
    inputs = [mx.sym.reshape(
        mx.sym.slice_axis(embed, axis=1, begin=t, end=t + 1),
        (-1, num_embed)) for t in range(SEQ)]
    outputs, _ = bi.unroll(SEQ, inputs=inputs, merge_outputs=True)
    # (N, SEQ, 2H) -> per-position class logits
    logits = mx.sym.FullyConnected(outputs, num_hidden=VOCAB,
                                   flatten=False, name="proj")
    logits = mx.sym.reshape(logits, (-1, VOCAB))       # (N*SEQ, V)
    label = mx.sym.reshape(mx.sym.Variable("softmax_label"), (-1,))
    return mx.sym.SoftmaxOutput(logits, label, name="softmax",
                                normalization="batch")


def main():
    p = argparse.ArgumentParser(description="bi-lstm sort")
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--num-examples", type=int, default=1500)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=11)
    args = p.parse_args()

    import mxnet_tpu as mx
    np.random.seed(args.seed)

    x, y = synth(args.num_examples)
    n_val = args.num_examples // 6
    train = mx.io.NDArrayIter(x[n_val:], y[n_val:],
                              batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(x[:n_val], y[:n_val],
                            batch_size=args.batch_size)
    mod = mx.mod.Module(get_symbol(), context=mx.cpu(0)
                        if not mx.num_devices("tpu") else mx.tpu(0))
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(), num_epoch=args.num_epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=None))

    val.reset()
    correct = total = 0
    for batch in val:
        mod.forward(batch, is_train=False)
        p_out = mod.get_outputs()[0].asnumpy().reshape(-1, SEQ, VOCAB)
        lbl = batch.label[0].asnumpy()
        keep = lbl.shape[0] - batch.pad       # drop pad-duplicated rows
        correct += (p_out.argmax(-1)[:keep] == lbl[:keep]).sum()
        total += lbl[:keep].size
    acc = correct / total
    print("per-position sort accuracy: %.4f (chance %.2f)"
          % (acc, 1.0 / VOCAB))
    assert acc > 0.6, "bi-lstm failed to learn sorting"
    return 0


if __name__ == "__main__":
    sys.exit(main())

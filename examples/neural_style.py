"""Neural style transfer: optimize the INPUT image, not the weights.

Capability twin of the reference's ``example/neural-style`` (Gatys et
al.): a fixed convolutional feature extractor defines a content loss
(deep feature match) and a style loss (Gram-matrix match), and
gradient descent runs on the *image pixels* — ``x.attach_grad()`` +
``autograd.record`` + manual updates, the gradient-wrt-input capability
the training APIs never exercise.

Fixed random conv features stand in for VGG (random-feature style
statistics are a known-good approximation, and this rig has no
pretrained-download egress); the gate checks the optimization moved the
image's Gram statistics decisively toward the style target while
keeping content correlation.

Run:  python examples/neural_style.py --num-steps 120
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_images(size=48, seed=0):
    """Content: centered disc. Style: diagonal stripes."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size] / float(size)
    content = np.stack([
        ((yy - 0.5) ** 2 + (xx - 0.5) ** 2 < 0.09).astype(np.float32),
        ((yy - 0.5) ** 2 + (xx - 0.5) ** 2 < 0.04).astype(np.float32),
        np.zeros((size, size), np.float32)])
    stripes = (np.sin((yy + xx) * 40) > 0).astype(np.float32)
    style = np.stack([stripes, 1 - stripes,
                      0.5 * np.ones((size, size), np.float32)])
    content += 0.05 * rng.rand(3, size, size).astype(np.float32)
    style += 0.05 * rng.rand(3, size, size).astype(np.float32)
    return content[None], style[None]


def main():
    p = argparse.ArgumentParser(description="neural style transfer")
    p.add_argument("--num-steps", type=int, default=120)
    p.add_argument("--size", type=int, default=48)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--style-weight", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    np.random.seed(args.seed)

    import mxnet_tpu as mx

    content, style = make_images(args.size)

    # fixed random conv stack: 2 feature levels
    rng = np.random.RandomState(3)
    W1 = mx.nd.array(rng.randn(16, 3, 3, 3).astype(np.float32) * 0.4)
    W2 = mx.nd.array(rng.randn(32, 16, 3, 3).astype(np.float32) * 0.2)

    def features(x):
        h1 = mx.nd.Activation(
            mx.nd.Convolution(x, W1, num_filter=16, kernel=(3, 3),
                              pad=(1, 1), no_bias=True),
            act_type="relu")
        h2 = mx.nd.Activation(
            mx.nd.Convolution(mx.nd.Pooling(h1, kernel=(2, 2),
                                            stride=(2, 2),
                                            pool_type="avg"),
                              W2, num_filter=32, kernel=(3, 3),
                              pad=(1, 1), no_bias=True),
            act_type="relu")
        return h1, h2

    def gram(f):
        n, c = f.shape[0], f.shape[1]
        flat = mx.nd.reshape(f, (n, c, -1))
        hw = flat.shape[2]
        return mx.nd.batch_dot(flat, flat, transpose_b=True) / float(hw)

    c_feats = [f.detach() if hasattr(f, "detach") else f
               for f in features(mx.nd.array(content))]
    s_grams = [gram(f) for f in features(mx.nd.array(style))]

    x = mx.nd.array(content.copy())
    x.attach_grad()

    def losses():
        f1, f2 = features(x)
        closs = mx.nd.mean(mx.nd.square(f2 - c_feats[1]))
        sloss = mx.nd.mean(mx.nd.square(gram(f1) - s_grams[0])) + \
            mx.nd.mean(mx.nd.square(gram(f2) - s_grams[1]))
        return closs, sloss

    c0, s0 = (float(v.asnumpy()) for v in losses())
    # the natural scale for "content survived": how far the STYLE image
    # is from the content features — the stylized result must stay much
    # closer to the content than that
    sf1, sf2 = features(mx.nd.array(style))
    c_of_style = float(mx.nd.mean(
        mx.nd.square(sf2 - c_feats[1])).asnumpy())
    for step in range(args.num_steps):
        with mx.autograd.record():
            closs, sloss = losses()
            loss = closs + args.style_weight * sloss
        loss.backward()
        # normalized gradient descent on the pixels (the reference uses
        # lr-decayed SGD over Adam-scale gradients; normalizing by the
        # mean |grad| makes the step size image-scale like theirs)
        g = x.grad.asnumpy()
        g /= np.abs(g).mean() + 1e-8
        x = mx.nd.array(np.clip(x.asnumpy() - args.lr * g, -0.2, 1.4))
        x.attach_grad()
        if step % 30 == 0:
            print("step %3d  content=%.5f style=%.5f"
                  % (step, float(closs.asnumpy()),
                     float(sloss.asnumpy())), flush=True)

    c1, s1 = (float(v.asnumpy()) for v in losses())
    print("style loss %.5f -> %.5f (%.1fx down); content %.5f "
          "(style image itself: %.5f)" % (s0, s1, s0 / max(s1, 1e-12),
                                          c1, c_of_style))
    assert s1 < 0.25 * s0, "style statistics did not move to the target"
    assert c1 < 0.5 * c_of_style, "content was destroyed"
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""DCGAN on synthetic glyphs — adversarial two-optimizer Gluon training.

Capability twin of the reference's ``example/gluon/dcgan.py``: a
Conv2DTranspose generator and a conv discriminator, each with its own
``gluon.Trainer``, alternating real/fake discriminator updates with
generator updates through ``autograd.record`` — the workflow that
exercises multiple optimizers over disjoint parameter sets in one
training loop.

Gates: the discriminator's real-vs-fake logit margin must grow (it is
learning to separate) and generated images' first moment must move
toward the data distribution from the noise prior.

Run:  python examples/dcgan.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from train_mnist import synth_mnist


def main():
    parser = argparse.ArgumentParser(description="gluon DCGAN")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--nz", type=int, default=32)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--num-examples", type=int, default=512)
    args = parser.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    ctx = mx.context.current_context()
    mx.random.seed(7)
    np.random.seed(7)

    # generator: latent -> 28x28 image in [0, 1]
    netG = nn.HybridSequential(prefix="gen_")
    with netG.name_scope():
        netG.add(nn.Dense(128 * 7 * 7, activation="relu"))
    deconv = nn.HybridSequential(prefix="gdec_")
    with deconv.name_scope():
        deconv.add(nn.Conv2DTranspose(64, kernel_size=4, strides=2,
                                      padding=1))    # 14x14
        deconv.add(nn.Activation("relu"))
        deconv.add(nn.Conv2DTranspose(1, kernel_size=4, strides=2,
                                      padding=1))    # 28x28
        deconv.add(nn.Activation("sigmoid"))

    def generate(z):
        h = netG(z).reshape((-1, 128, 7, 7))
        return deconv(h)

    # discriminator: image -> real/fake logit
    netD = nn.HybridSequential(prefix="disc_")
    with netD.name_scope():
        netD.add(nn.Conv2D(32, kernel_size=4, strides=2, padding=1))
        netD.add(nn.LeakyReLU(0.2))
        netD.add(nn.Conv2D(64, kernel_size=4, strides=2, padding=1))
        netD.add(nn.LeakyReLU(0.2))
        netD.add(nn.Flatten())
        netD.add(nn.Dense(1))

    for net in (netG, deconv, netD):
        net.initialize(mx.init.Normal(0.02), ctx=ctx)

    g_params = gluon.ParameterDict()
    g_params.update(netG.collect_params())
    g_params.update(deconv.collect_params())
    trainerG = gluon.Trainer(g_params, "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    x, _ = synth_mnist(args.num_examples, seed=3)
    B = args.batch_size
    if B > len(x):
        parser.error("--batch-size %d exceeds --num-examples %d"
                     % (B, len(x)))
    rng = np.random.RandomState(0)
    real_label = mx.nd.array(np.ones(B, np.float32), ctx=ctx)
    fake_label = mx.nd.array(np.zeros(B, np.float32), ctx=ctx)

    margin_hist = []
    for epoch in range(args.num_epochs):
        perm = np.random.permutation(len(x))
        margins = []
        for s in range(0, len(x) - B + 1, B):
            real = mx.nd.array(x[perm[s:s + B]], ctx=ctx)
            z = mx.nd.array(rng.normal(0, 1, (B, args.nz))
                            .astype(np.float32), ctx=ctx)
            # --- discriminator step: real up, fake down
            with autograd.record():
                out_real = netD(real).reshape((-1,))
                fake = generate(z)
                out_fake = netD(fake.detach()).reshape((-1,))
                lossD = loss_fn(out_real, real_label) + \
                    loss_fn(out_fake, fake_label)
            lossD.backward()
            trainerD.step(B)
            # --- generator step: make D call fakes real
            with autograd.record():
                fake = generate(z)
                out = netD(fake).reshape((-1,))
                lossG = loss_fn(out, real_label)
            lossG.backward()
            trainerG.step(B)
            margins.append(float(out_real.asnumpy().mean())
                           - float(out_fake.asnumpy().mean()))
        margin_hist.append(float(np.mean(margins)))
        print("epoch %d  D margin %.4f  lossD %.3f  lossG %.3f"
              % (epoch, margin_hist[-1],
                 float(lossD.asnumpy().mean()),
                 float(lossG.asnumpy().mean())))

    z = mx.nd.array(rng.normal(0, 1, (B, args.nz)).astype(np.float32),
                    ctx=ctx)
    samples = generate(z).asnumpy()
    assert np.isfinite(samples).all(), "generator produced non-finite"
    gen_mean = samples.mean()
    data_mean = x.mean()
    print("generated mean %.3f vs data mean %.3f (noise prior ~0.5)"
          % (gen_mean, data_mean))
    # the adversarial game must be live: D separates real from fake
    assert margin_hist[-1] > 0.02, margin_hist
    assert abs(gen_mean - data_mean) < 0.25, \
        "generated statistics did not move toward the data"
    return 0


if __name__ == "__main__":
    sys.exit(main())

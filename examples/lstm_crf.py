"""BiLSTM-CRF sequence tagger with a dynamic-programming loss.

Capability twin of the reference's ``example/gluon/lstm_crf.py``: a
bidirectional LSTM emits per-token tag scores, a CRF layer learns tag
transition scores, training minimizes the CRF negative log-likelihood
(the partition function computed by the forward algorithm — a
logsumexp dynamic program over the sequence), and decoding runs
Viterbi (a max-sum dynamic program). Built TPU-first: both dynamic
programs are plain tensor recurrences over ``mx.nd`` ops driven by
autograd, so the whole loss differentiates end to end.

The task is synthetic BIO-style tagging with strong transition
structure (tag grammar: O -> B -> I -> I ... -> O), so the CRF's
transition matrix is load-bearing: an emission-only tagger cannot
reach the gate.

Run:  python examples/lstm_crf.py --num-epochs 12
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB, TAGS, T = 20, 3, 12   # tags: 0=O 1=B 2=I


def synth_tagging(n, seed=0):
    """Tokens 0-9 are 'outside' words; 10-14 begin an entity; 15-19
    continue one. Tags follow: B after a trigger token, I while inside,
    O otherwise — learnable emissions, but I-without-B never happens,
    which only the transition matrix can express."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, T), np.float32)
    Y = np.zeros((n, T), np.int64)
    for i in range(n):
        t = 0
        while t < T:
            if rng.rand() < 0.3 and t < T - 2:
                L = rng.randint(2, min(4, T - t))
                X[i, t] = rng.randint(10, 15)
                Y[i, t] = 1
                for k in range(1, L):
                    X[i, t + k] = rng.randint(15, 20)
                    Y[i, t + k] = 2
                t += L
            else:
                X[i, t] = rng.randint(0, 10)
                Y[i, t] = 0
                t += 1
    return X, Y


def crf_log_likelihood(emissions, transitions, tags):
    """CRF NLL via the forward algorithm (reference lstm_crf.py
    _forward_alg / _score_sentence, re-expressed as batched tensor
    recurrences). emissions: list of T (B, K); tags: (B, T) int."""
    import mxnet_tpu as mx
    B, K = emissions[0].shape
    # score of the gold path
    gold = None
    prev = None
    for t in range(T):
        tag_t = tags[:, t]
        emit = mx.nd.pick(emissions[t], mx.nd.array(tag_t), axis=1)
        s = emit
        if prev is not None:
            idx = np.stack([prev, tag_t], axis=0)
            s = s + mx.nd.gather_nd(transitions, mx.nd.array(idx))
        gold = s if gold is None else gold + s
        prev = tag_t
    # partition: alpha recurrence with logsumexp
    alpha = emissions[0]                                   # (B, K)
    trans = mx.nd.expand_dims(transitions, 0)              # (1, K, K)
    for t in range(1, T):
        prev_a = mx.nd.expand_dims(alpha, 2)               # (B, K, 1)
        emit = mx.nd.expand_dims(emissions[t], 1)          # (B, 1, K)
        scores = mx.nd.broadcast_add(
            mx.nd.broadcast_add(prev_a, trans), emit)      # (B, K, K)
        m = mx.nd.max(scores, axis=1, keepdims=True)
        alpha = mx.nd.squeeze(m, axis=1) + mx.nd.log(
            mx.nd.sum(mx.nd.exp(mx.nd.broadcast_sub(scores, m)), axis=1))
    m = mx.nd.max(alpha, axis=1, keepdims=True)
    logZ = mx.nd.squeeze(m, axis=1) + mx.nd.log(
        mx.nd.sum(mx.nd.exp(mx.nd.broadcast_sub(alpha, m)), axis=1))
    return mx.nd.mean(logZ - gold)


def viterbi(emissions, transitions):
    """Max-sum decode; emissions: list of T (B, K) numpy."""
    trans = transitions
    B, K = emissions[0].shape
    score = emissions[0]
    back = []
    for t in range(1, T):
        cand = score[:, :, None] + trans[None] + emissions[t][:, None, :]
        back.append(cand.argmax(axis=1))                   # (B, K)
        score = cand.max(axis=1)
    path = [score.argmax(axis=1)]
    for bp in reversed(back):
        path.append(bp[np.arange(B), path[-1]])
    return np.stack(path[::-1], axis=1)


def main():
    p = argparse.ArgumentParser(description="BiLSTM-CRF tagger")
    p.add_argument("--num-epochs", type=int, default=12)
    p.add_argument("--num-examples", type=int, default=300)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args()
    np.random.seed(args.seed)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    X, Y = synth_tagging(args.num_examples, seed=1)
    Xv, Yv = synth_tagging(80, seed=2)

    class Tagger(gluon.Block):
        def __init__(self, **kw):
            super(Tagger, self).__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(VOCAB, args.hidden)
                self.lstm = gluon.rnn.LSTM(args.hidden // 2, num_layers=1,
                                           bidirectional=True,
                                           layout="NTC")
                self.proj = nn.Dense(TAGS, flatten=False)
                self.transitions = self.params.get(
                    "transitions", shape=(TAGS, TAGS), init=mx.init.Zero())

        def emissions(self, x):
            h = self.lstm(self.embed(x))
            return self.proj(h)                            # (B, T, K)

    net = Tagger()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    bs = 50
    for epoch in range(args.num_epochs):
        tot = 0.0
        for i in range(0, len(X), bs):
            xb = mx.nd.array(X[i:i + bs])
            yb = Y[i:i + bs]
            with mx.autograd.record():
                em = net.emissions(xb)
                ems = [mx.nd.squeeze(mx.nd.slice_axis(
                    em, axis=1, begin=t, end=t + 1), axis=1)
                    for t in range(T)]
                loss = crf_log_likelihood(
                    ems, net.transitions.data(), yb)
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print("Epoch[%d] crf-nll=%.4f" % (epoch, tot / (len(X) / bs)),
              flush=True)

    em = net.emissions(mx.nd.array(Xv)).asnumpy()
    ems = [em[:, t] for t in range(T)]
    pred = viterbi(ems, net.transitions.data().asnumpy())
    acc = float((pred == Yv).mean())
    # structural check: the learned transitions must forbid O -> I
    trans = net.transitions.data().asnumpy()
    print("tag accuracy: %.4f  (O->I score %.2f vs O->B %.2f)"
          % (acc, trans[0, 2], trans[0, 1]))
    assert acc > 0.9, "CRF tagger failed to learn"
    assert trans[0, 2] < trans[0, 1], \
        "transition matrix did not learn the tag grammar"
    return 0


if __name__ == "__main__":
    sys.exit(main())

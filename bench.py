"""Driver benchmark: ResNet-50 fused training step, images/sec on one chip,
plus a transformer-LM train step as the MXU-bound secondary workload.

Baseline: the reference's published training number for ResNet-50 at batch 32
— 181.53 img/s on P100 (BASELINE.md, docs/how_to/perf.md:180-190). This
script runs the same workload through the TPU-native stack: one fused
forward+backward+SGD-update XLA program built by Module._build_fused_step,
in bf16 mixed precision (fp32 master weights, bf16 MXU compute — mx.amp).

ResNet-50's small-spatial convs cap out near ~29% MFU under XLA on this
chip (a hand-written pure-JAX ResNet measures ~26% on the same hardware;
the chip's pure-matmul marginal rate measures ~93% of nominal peak), so
the bench also reports a transformer LM (models/transformer.py) through
the identical Module fused-step path — the workload class whose large
matmuls can actually feed the MXU.

Wedge-proofing (round-5 top item): each workload runs as its own
*section* in a child process with its own timeout, and every section's
JSON record is printed (and flushed) the moment it completes — so a
tunnel-wedge hang or an external kill loses ONE section, not the whole
artifact (round 5: rc 124 left BENCH_r05.json empty). Output protocol:

  {"section": "resnet", ...}        <- line per section, as it finishes
  {"section": "transformer", ...}
  {"metric": ..., "value": ...}     <- LAST line: merged record, the
                                       schema previous rounds consumed

Consumers that take the last line keep working; consumers that want
partial results on a wedge read the section lines.
Per-section timeout: $BENCH_SECTION_TIMEOUT_SECS (default 600).

Round-9 wedge-class fix: sections additionally run every bind/compile
under a PhaseGuard — a hard per-phase deadline
($BENCH_BIND_TIMEOUT_SECS, default 300) INSIDE the section process that,
on expiry, prints a partial record carrying the phase name and the
bind_secs burned so far, then exits 124. The round-5 failure mode ("
resnet bind start" then 25 silent minutes, whole section lost) now
leaves a diagnosable partial line, and the parent keeps partial records
from non-zero-rc sections instead of discarding their stdout.

Compile-time levers (ISSUE 9) measured here: the transformer section
binds with scan-over-layers on AND off to record the bind/first-step
delta; the resnet_remat_accum section retries ResNet at 2x batch with
MXNET_TPU_REMAT=auto + grad_accum=2 (HBM headroom -> MFU).
"""
import json
import os
import subprocess
import sys
import threading
import time


def _note(msg):
    print(msg, file=sys.stderr, flush=True)

sys.path.insert(0, __file__.rsplit("/", 1)[0] if "/" in __file__ else ".")


class PhaseGuard:
    """Hard per-phase deadline inside a section process.

    ``with guard.phase("bind"):`` arms a watchdog; if the phase is still
    running after ``timeout`` seconds the guard prints ``rec`` (the
    section's partial record, filled incrementally) plus the phase name
    and elapsed seconds as the section's ONLY record line, then
    ``os._exit(124)`` — the parent keeps this partial line, so a wedged
    bind no longer erases the measurements that preceded it."""

    def __init__(self, section, rec, timeout=None):
        self.section = section
        self.rec = rec
        self.timeout = float(timeout if timeout is not None else
                             os.environ.get("BENCH_BIND_TIMEOUT_SECS",
                                            "300"))
        self._deadline = None
        self._name = None
        self._lock = threading.Lock()
        t = threading.Thread(target=self._watch, daemon=True,
                             name="bench-phase-guard")
        t.start()

    def _watch(self):
        while True:
            time.sleep(0.5)
            with self._lock:
                dl, name = self._deadline, self._name
            if dl is None:
                continue
            now = time.perf_counter()
            if now >= dl:
                out = dict(self.rec)
                out["section"] = self.section
                out["phase"] = name
                out["phase_elapsed_secs"] = round(now - (dl - self.timeout),
                                                  1)
                # only fill bind_secs when the bind itself is what
                # wedged — a completed arm's real measurement in rec
                # must survive (the whole point of the partial record)
                out.setdefault("bind_secs", out["phase_elapsed_secs"])
                out["error"] = "phase %r exceeded %ds" % (name,
                                                          self.timeout)
                print(json.dumps(out), flush=True)
                os._exit(124)

    class _Phase:
        def __init__(self, guard, name):
            self.guard, self.name = guard, name

        def __enter__(self):
            with self.guard._lock:
                self.guard._name = self.name
                self.guard._deadline = time.perf_counter() + \
                    self.guard.timeout
            return self

        def __exit__(self, *exc):
            with self.guard._lock:
                self.guard._deadline = None
            return False

    def phase(self, name):
        return PhaseGuard._Phase(self, name)


BASELINE_IMG_S = 181.53   # P100 training, ResNet-50 batch 32
# Round-6 shrink: round 5 timed out (rc 124) with the resnet section at
# "bind start" for 25+ min on the axon platform — batch 128 and a shorter
# timed window keep the whole section inside BENCH_SECTION_TIMEOUT_SECS
# while img/s (a per-image rate) stays comparable across rounds; bind_secs
# is now recorded per section so bind-time regressions show up in the
# trajectory instead of as silent timeouts.
BATCH = 128
WARMUP = 2
ITERS = 12
SECTIONS = ("resnet", "resnet_remat_accum", "transformer")

# Analytic model FLOPs: ResNet-50 @224x224 forward = 4.089e9 multiply-adds
# (= 8.18 GFLOP at 2 FLOPs/MAC); training step ~ 3x forward (fwd + 2x in bwd).
FWD_MACS_PER_IMG = 4.089e9
TRAIN_FLOPS_PER_IMG = 2 * FWD_MACS_PER_IMG * 3

def _peak_flops(device_kind: str):
    # the device-kind -> peak table is shared with mx.obs (constants must
    # not drift between the two MFU computations); the RATE and FLOP math
    # here stay independent — that independence is what makes the
    # obs_mfu cross-check meaningful
    from mxnet_tpu.obs.mfu import PEAK_FLOPS_BY_DEVICE_KIND
    dk = device_kind.lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if sub in dk:
            return peak
    return None  # unknown device: report img/s only, no fabricated MFU


def _obs_crosscheck():
    """Framework-side MFU/compile accounting (mx.obs), reported next to
    this script's independent math: report() here closes the rate window
    the post-warmup report() opened, so the obs steps/s covers exactly
    the timed region. Divergence >10% between obs_mfu and the section's
    own mfu is a bug in one of them — that is the point of recording
    both (ISSUE 6 acceptance)."""
    import mxnet_tpu as mx
    rep = mx.obs.report()
    best = None
    for e in rep["executors"]:
        if e.get("flops_per_sec") and \
                (best is None or e["flops_per_sec"] > best["flops_per_sec"]):
            best = e
    return {
        "obs_mfu": round(best["mfu"], 4)
        if best and best.get("mfu") is not None else None,
        "obs_flops_per_sec": best["flops_per_sec"] if best else None,
        "obs_compile_count": rep["counters"].get("obs_compile_count"),
        "obs_bind_ms_total": rep["counters"].get("obs_bind_ms_total"),
    }


def _tune_provenance():
    """Where this section's config came from (ISSUE 19): ``tuned`` is
    True when an autotuner winner was applied in this process
    (``tune_applied`` counter — fit(tune=...) or MXNET_TPU_TUNE), and
    ``tune_knobs`` is the knob dict actually in effect either way, so a
    tuner-vs-hand-tuned bench delta is attributable to specific knobs
    rather than 'the tuner ran'."""
    import mxnet_tpu as mx
    return {
        "tuned": bool(mx.profiler.counters().get("tune_applied")),
        "tune_knobs": {k: mx.config.get(k) for k in (
            "MXNET_TPU_REMAT", "MXNET_TPU_SCAN_LAYERS",
            "MXNET_TPU_GROUP_UPDATE", "MXNET_TPU_ASYNC_WINDOW")},
    }


def section_transformer():
    """Transformer-LM fused train step: tokens/s + MFU on one chip, and
    the deep-model compile-time delta: bind + first-step wall with
    scan-over-layers ON (the default) vs OFF (unrolled), each arm under
    its own PhaseGuard so a wedged unrolled bind cannot erase the scan
    numbers (the round-5 wedge class)."""
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer

    if not mx.num_devices("tpu"):
        return {"skipped": "no tpu attached"}
    peak = _peak_flops(jax.devices()[0].device_kind)
    mx.amp.init("bfloat16")
    # ~0.67B-param GPT-2-medium-class decoder LM with the Pallas flash
    # attention kernel (fused fwd + dQ/dK/dV backward). Measured sweep on
    # this chip (see docs/perf.md): flash beats dense batch_dot attention
    # and L12/B8 is the MFU sweet spot; deeper/wider configs (1.5B) hit
    # the HBM ceiling with f32 master weights.
    L, D, H, T, V = 12, 2048, 16, 1024, 32000
    B = 8
    rec = {}
    guard = PhaseGuard("transformer", rec)
    sym = transformer.get_symbol(vocab_size=V, num_layers=L, d_model=D,
                                 n_heads=H, seq_len=T, attention="flash")
    rng = np.random.RandomState(0)
    x = rng.randint(0, V, (B, T)).astype(np.float32)
    y = rng.randint(0, V, (B, T)).astype(np.float32)

    def build_and_first_step(scan_mode, phase):
        mx.config.set("MXNET_TPU_SCAN_LAYERS", scan_mode)
        _note("bench: transformer bind start (scan=%s)" % scan_mode)
        with guard.phase(phase):
            t_bind = time.perf_counter()
            mod = mx.mod.Module(sym, context=mx.tpu(0))
            mod.bind(data_shapes=[("data", (B, T))],
                     label_shapes=[("softmax_label", (B, T))])
            mod.init_params(mx.init.Xavier())
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.01})
            bind_secs = round(time.perf_counter() - t_bind, 3)
            db = mx.io.DataBatch(data=[mx.nd.array(x, ctx=mx.tpu(0))],
                                 label=[mx.nd.array(y, ctx=mx.tpu(0))])
            _note("bench: transformer bound in %.1fs; compiling" % bind_secs)
            t0 = time.perf_counter()
            mod._fit_step(db)
            float(np.asarray(
                mod._exec.arg_dict["lm_head_weight"].data[0, 0]))
            first_step = round(time.perf_counter() - t0, 3)
        return mod, db, bind_secs, first_step

    mod, db, bind_on, first_on = build_and_first_step("auto", "bind-scan")
    rec["bind_secs"] = bind_on
    rec["first_step_secs"] = first_on
    rec["scan_layers"] = mx.profiler.gauges().get("scan_layers")

    def drain():
        return float(np.asarray(
            mod._exec.arg_dict["lm_head_weight"].data[0, 0]))

    with guard.phase("warmup"):
        mod._fit_step(db)
        drain()
    mx.obs.report()     # open the obs rate window at the timed region
    _note("bench: transformer timing")
    iters = 12
    t0 = time.perf_counter()
    for _ in range(iters):
        mod._fit_step(db)
    drain()
    dt = time.perf_counter() - t0
    tok_s = B * T * iters / dt
    # PaLM-style accounting: 6*(non-embedding params) + 12*L*D*T per token
    n_params = transformer.param_count(V, L, D, H, seq_len=T)
    n_embed = V * D + T * D
    flops_per_tok = 6 * (n_params - n_embed) + 12 * L * D * T
    mfu = round(tok_s * flops_per_tok / peak, 4) if peak else None
    rec.update({"transformer_tok_s": round(tok_s, 1),
                "transformer_mfu": mfu})
    rec.update(_obs_crosscheck())
    rec.update(_tune_provenance())

    # the unrolled control arm LAST (it is the wedge-prone one — round 5
    # died in exactly this bind); its guard exit keeps everything above
    if os.environ.get("BENCH_SCAN_OFF_ARM", "1") != "0":
        del mod, db
        try:
            _, _, bind_off, first_off = build_and_first_step(
                "off", "bind-unrolled")
            rec["bind_secs_scan_off"] = bind_off
            rec["first_step_secs_scan_off"] = first_off
            on, off = bind_on + first_on, bind_off + first_off
            rec["scan_bind_speedup"] = round(off / on, 2) if on else None
        finally:
            mx.config.set("MXNET_TPU_SCAN_LAYERS", "auto")
    return rec


def _resnet_run(rec, batch, iters, grad_accum=None, remat=None,
                section="resnet", virtual_mesh=False, layers=50,
                image=224, guard_timeout=None):
    """Shared ResNet bf16 driver: bind (phase-guarded), warm up, time
    the fused step, fill ``rec`` in place (partial values survive a
    guard exit). ``virtual_mesh`` = data-parallel over every visible
    (virtual CPU) device — the no-TPU fallback rig."""
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    on_tpu = bool(mx.num_devices("tpu"))
    if virtual_mesh and not on_tpu:
        ndev = len(jax.devices())
        ctx = [mx.cpu(i) for i in range(ndev)] if ndev > 1 else mx.cpu(0)
        rec["n_devices"] = ndev
    else:
        ctx = mx.tpu(0) if on_tpu else mx.cpu(0)
    guard = PhaseGuard(section, rec, timeout=guard_timeout)

    mx.amp.init("bfloat16")   # bf16 MXU compute, fp32 master weights
    if remat is not None:
        mx.config.set("MXNET_TPU_REMAT", remat)
    _note("bench: %s bind start" % section)
    with guard.phase("bind"):
        t_bind = time.perf_counter()
        # space-to-depth stem: mathematically identical to the 7x7/2
        # stem on the same parameter, ~2 ms/step faster (docs/perf.md
        # round-5 restructuring sweep)
        sym = resnet.get_symbol(num_classes=1000, num_layers=layers,
                                stem="s2d",
                                image_shape="3,%d,%d" % (image, image))
        mod = mx.mod.Module(sym, context=ctx)
        mod.bind(data_shapes=[("data", (batch, 3, image, image))],
                 label_shapes=[("softmax_label", (batch,))])
        mod.init_params(mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2))
        if grad_accum:
            mod.set_grad_accum(grad_accum)
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "momentum": 0.9, "wd": 1e-4})
        rec["bind_secs"] = round(time.perf_counter() - t_bind, 3)
    _note("bench: %s bound in %.1fs" % (section, rec["bind_secs"]))

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (batch, 3, image, image)).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.float32)
    host_ctx = ctx[0] if isinstance(ctx, list) else ctx
    dbatch = mx.io.DataBatch(data=[mx.nd.array(x, ctx=host_ctx)],
                             label=[mx.nd.array(y, ctx=host_ctx)])

    def drain():
        # On the experimental remote-TPU plugin this machine uses,
        # block_until_ready returns before execution finishes — measured:
        # fencing with block_until_ready alone reported 147k img/s
        # (18x the chip's physical bf16 peak, impossible), while this
        # host read reports 2.2k img/s. Standard backends don't need
        # this; keep the host read as the fence wherever this bench runs.
        return float(np.asarray(
            mod._exec.arg_dict["fc1_weight"].data[0, 0]))

    _note("bench: %s compiling" % section)
    with guard.phase("compile"):
        t0 = time.perf_counter()
        for _ in range(WARMUP):
            mod._fit_step(dbatch)
        drain()
        rec["first_step_secs"] = round(time.perf_counter() - t0, 3)
    mx.obs.report()     # open the obs rate window at the timed region
    _note("bench: %s timing" % section)

    rc0 = mx.profiler.counters().get("loop_recompile", 0)
    t0 = time.perf_counter()
    for _ in range(iters):
        mod._fit_step(dbatch)
    drain()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    peak = _peak_flops(jax.devices()[0].device_kind) if on_tpu else None
    mfu = round(img_s * TRAIN_FLOPS_PER_IMG / peak, 4) if peak else None
    counters = mx.profiler.counters()
    rec.update({
        "metric": "resnet50_train_bf16",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": mfu,
        "batch": batch,
        "flops_per_img": TRAIN_FLOPS_PER_IMG,
        "peak_flops": peak,
        # steady-state recompiles are a bug; record the timed window's
        # delta so the acceptance gate can counter-assert zero
        "loop_recompile": counters.get("loop_recompile", 0) - rc0,
        "remat_applied": counters.get("remat_applied", 0),
        "accum_steps": counters.get("accum_steps", 0),
    })
    rec.update(_obs_crosscheck())
    rec.update(_tune_provenance())
    return rec


def section_resnet():
    on_tpu_batch = BATCH
    import mxnet_tpu as mx
    on_tpu = bool(mx.num_devices("tpu"))
    batch = on_tpu_batch if on_tpu else 8
    iters = ITERS if on_tpu else 3
    return _resnet_run({}, batch, iters, section="resnet")


def section_resnet_remat_accum():
    """The ISSUE 9 memory levers applied: 2x the round-5 batch, fit in
    HBM via auto-remat + 2-way gradient accumulation, MFU vs the 0.29
    plain-batch baseline.

    No-TPU fallback (ISSUE 14, retiring the BENCH_r05 rc-124 note): the
    section used to ship EMPTY whenever the TPU tunnel was unreachable —
    rounds 5-13 never carried a resnet_remat_accum record at all. Now it
    runs the same levers on the host (8-device virtual CPU mesh, small
    batch) and records a clearly-labeled fallback line: img/s is a
    CPU number (never compare against TPU rounds — the `fallback` key
    marks it), but the remat_applied/accum_steps/loop_recompile counters
    prove the levers engaged, so the section never again ships empty."""
    # the fallback needs the virtual mesh; the flag must land before
    # jax initializes in this section's child process. 2 devices, not 8:
    # SPMD-partitioning ResNet-50 (+ remat + the accum scan) for 8
    # virtual CPU devices blows the 300s PhaseGuard compile budget —
    # 2 still proves mesh + levers compose and compiles in budget
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] in ("", "cpu") \
            and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                     "device_count=2")
    import mxnet_tpu as mx
    on_tpu = bool(mx.num_devices("tpu"))
    if on_tpu:
        return _resnet_run({}, 2 * BATCH, ITERS, grad_accum=2,
                           remat="auto", section="resnet_remat_accum")
    rec = {"fallback": "cpu-virtual-mesh",
           "fallback_model": "resnet18@112",
           "note": "no tpu attached; levers exercised on the virtual "
                   "CPU mesh so the record is never empty — a RESNET-18 "
                   "@112px CPU number, NOT comparable to the TPU "
                   "resnet50 rounds (XLA-CPU compiles the accum scan of "
                   "resnet50@224 in ~300s+, past the phase budget; "
                   "r18@112 x 2 devices compiles in ~80s)"}
    rec = _resnet_run(rec, 16, 2, grad_accum=2, remat="auto",
                      section="resnet_remat_accum", virtual_mesh=True,
                      layers=18, image=112, guard_timeout=450)
    # a fallback record must never masquerade as the TPU numbers (and
    # the analytic flops constant is resnet50's, not resnet18's)
    rec["mfu"] = None
    rec["vs_baseline"] = None
    rec["flops_per_img"] = None
    return rec


def run_section(name):
    fn = {"resnet": section_resnet,
          "resnet_remat_accum": section_resnet_remat_accum,
          "transformer": section_transformer}[name]
    rec = dict(fn())
    rec["section"] = name
    print(json.dumps(rec), flush=True)


def _merge(records):
    """Assemble the flat single-record schema previous rounds consumed
    from whatever sections survived."""
    merged = {
        "metric": "resnet50_train_bf16", "value": None, "unit": "img/s",
        "vs_baseline": None, "mfu": None, "batch": None,
        "flops_per_img": TRAIN_FLOPS_PER_IMG, "peak_flops": None,
        "transformer_tok_s": None, "transformer_mfu": None,
        "resnet_remat_accum_mfu": None, "resnet_remat_accum_img_s": None,
        "scan_bind_speedup": None,
        "bind_secs": {},
        "first_step_secs": {},
        "obs_mfu": {},
        "obs_bind_ms_total": {},
        "tuned": {},
        "tune_knobs": {},
    }
    _per_section = ("bind_secs", "first_step_secs", "obs_mfu",
                    "obs_bind_ms_total", "tuned", "tune_knobs")
    errors = {}
    for name, rec in records.items():
        if "error" in rec and not any(
                rec.get(k) is not None for k in _per_section):
            errors[name] = rec["error"]
            continue
        if "error" in rec:
            # partial record (PhaseGuard exit): keep its measurements
            # AND surface the error
            errors[name] = rec["error"]
        if name == "resnet_remat_accum":
            merged["resnet_remat_accum_mfu"] = rec.get("mfu")
            merged["resnet_remat_accum_img_s"] = rec.get("value")
        else:
            for k in merged:
                if k not in _per_section and k in rec:
                    merged[k] = rec[k]
        for k in _per_section:
            # per-section records: the round-5 wedge was a 25-min bind,
            # invisible in a throughput-only record; obs_mfu is the
            # framework's own MFU next to this script's independent math
            if rec.get(k) is not None:
                merged[k][name] = rec[k]
    if errors:
        merged["errors"] = errors
    return merged


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--section":
        run_section(sys.argv[2])
        return
    timeout = float(os.environ.get("BENCH_SECTION_TIMEOUT_SECS", "600"))
    records = {}
    for name in SECTIONS:
        # the no-TPU resnet_remat_accum fallback legitimately spends up
        # to its 450s guard inside ONE compile; give the section head
        # room so the guard (which leaves a partial record) fires before
        # the parent timeout (which loses everything)
        # ... and never below the guard + exit slack, or a lowered
        # BENCH_SECTION_TIMEOUT_SECS would let the parent kill land
        # first and lose the partial record the guard exists to save
        sect_timeout = max(timeout * 1.5, 510) \
            if name == "resnet_remat_accum" else timeout
        _note("bench: section %s (timeout %ds)" % (name, sect_timeout))
        rec = {"section": name}
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--section", name],
                timeout=sect_timeout, stdout=subprocess.PIPE, text=True)
            lines = [l for l in (proc.stdout or "").splitlines()
                     if l.strip()]
            parsed = None
            for line in reversed(lines):
                try:
                    candidate = json.loads(line)
                except ValueError:
                    continue
                if isinstance(candidate, dict):
                    parsed = candidate
                    break
            if parsed is not None:
                # keep partial records from non-zero exits too: a
                # PhaseGuard bind-timeout exit (rc 124) prints the
                # section's measurements so far — round 5 lost them
                rec = parsed
                if proc.returncode != 0:
                    rec.setdefault("error", "rc %d" % proc.returncode)
            elif proc.returncode != 0:
                rec["error"] = "rc %d" % proc.returncode
            else:
                rec["error"] = "no output"
        except subprocess.TimeoutExpired:
            # the wedge case: this section hung; its sibling sections
            # still run and still report
            rec["error"] = "timeout after %ds" % timeout
        except Exception as exc:                           # noqa: BLE001
            rec["error"] = "%s: %s" % (type(exc).__name__, exc)
        records[name] = rec
        # incremental line-per-section: flushed NOW, so a later wedge
        # cannot take this section's result with it
        print(json.dumps(rec), flush=True)
    print(json.dumps(_merge(records)), flush=True)


if __name__ == "__main__":
    main()

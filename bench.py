"""Driver benchmark: ResNet-50 fused training step, images/sec on one chip.

Baseline: the reference's published training number for ResNet-50 at batch 32
— 181.53 img/s on P100 (BASELINE.md, docs/how_to/perf.md:180-190). This
script runs the same workload through the TPU-native stack: one fused
forward+backward+SGD-update XLA program built by Module._build_fused_step.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N/181.53}
"""
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0] if "/" in __file__ else ".")

BASELINE_IMG_S = 181.53   # P100 training, ResNet-50 batch 32
BATCH = 32
WARMUP = 3
ITERS = 20


def main():
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    ctx = mx.tpu(0) if mx.num_devices("tpu") else mx.cpu(0)

    sym = resnet.get_symbol(num_classes=1000, num_layers=50)
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[("data", (BATCH, 3, 224, 224))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9, "wd": 1e-4})

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (BATCH, 3, 224, 224)).astype(np.float32)
    y = rng.randint(0, 1000, (BATCH,)).astype(np.float32)
    batch = mx.io.DataBatch(data=[mx.nd.array(x, ctx=ctx)],
                            label=[mx.nd.array(y, ctx=ctx)])

    for _ in range(WARMUP):
        mod._fit_step(batch)
    jax.block_until_ready(mod._exec.arg_dict["fc1_weight"].data)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        mod._fit_step(batch)
    jax.block_until_ready(mod._exec.arg_dict["fc1_weight"].data)
    dt = time.perf_counter() - t0

    img_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_batch32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()

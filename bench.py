"""Driver benchmark: ResNet-50 fused training step, images/sec on one chip,
plus a transformer-LM train step as the MXU-bound secondary workload.

Baseline: the reference's published training number for ResNet-50 at batch 32
— 181.53 img/s on P100 (BASELINE.md, docs/how_to/perf.md:180-190). This
script runs the same workload through the TPU-native stack: one fused
forward+backward+SGD-update XLA program built by Module._build_fused_step,
in bf16 mixed precision (fp32 master weights, bf16 MXU compute — mx.amp).

ResNet-50's small-spatial convs cap out near ~29% MFU under XLA on this
chip (a hand-written pure-JAX ResNet measures ~26% on the same hardware;
the chip's pure-matmul marginal rate measures ~93% of nominal peak), so
the bench also reports a transformer LM (models/transformer.py) through
the identical Module fused-step path — the workload class whose large
matmuls can actually feed the MXU.

Wedge-proofing (round-5 top item): each workload runs as its own
*section* in a child process with its own timeout, and every section's
JSON record is printed (and flushed) the moment it completes — so a
tunnel-wedge hang or an external kill loses ONE section, not the whole
artifact (round 5: rc 124 left BENCH_r05.json empty). Output protocol:

  {"section": "resnet", ...}        <- line per section, as it finishes
  {"section": "transformer", ...}
  {"metric": ..., "value": ...}     <- LAST line: merged record, the
                                       schema previous rounds consumed

Consumers that take the last line keep working; consumers that want
partial results on a wedge read the section lines.
Per-section timeout: $BENCH_SECTION_TIMEOUT_SECS (default 600).
"""
import json
import os
import subprocess
import sys
import time


def _note(msg):
    print(msg, file=sys.stderr, flush=True)

sys.path.insert(0, __file__.rsplit("/", 1)[0] if "/" in __file__ else ".")

BASELINE_IMG_S = 181.53   # P100 training, ResNet-50 batch 32
# Round-6 shrink: round 5 timed out (rc 124) with the resnet section at
# "bind start" for 25+ min on the axon platform — batch 128 and a shorter
# timed window keep the whole section inside BENCH_SECTION_TIMEOUT_SECS
# while img/s (a per-image rate) stays comparable across rounds; bind_secs
# is now recorded per section so bind-time regressions show up in the
# trajectory instead of as silent timeouts.
BATCH = 128
WARMUP = 2
ITERS = 12
SECTIONS = ("resnet", "transformer")

# Analytic model FLOPs: ResNet-50 @224x224 forward = 4.089e9 multiply-adds
# (= 8.18 GFLOP at 2 FLOPs/MAC); training step ~ 3x forward (fwd + 2x in bwd).
FWD_MACS_PER_IMG = 4.089e9
TRAIN_FLOPS_PER_IMG = 2 * FWD_MACS_PER_IMG * 3

def _peak_flops(device_kind: str):
    # the device-kind -> peak table is shared with mx.obs (constants must
    # not drift between the two MFU computations); the RATE and FLOP math
    # here stay independent — that independence is what makes the
    # obs_mfu cross-check meaningful
    from mxnet_tpu.obs.mfu import PEAK_FLOPS_BY_DEVICE_KIND
    dk = device_kind.lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if sub in dk:
            return peak
    return None  # unknown device: report img/s only, no fabricated MFU


def _obs_crosscheck():
    """Framework-side MFU/compile accounting (mx.obs), reported next to
    this script's independent math: report() here closes the rate window
    the post-warmup report() opened, so the obs steps/s covers exactly
    the timed region. Divergence >10% between obs_mfu and the section's
    own mfu is a bug in one of them — that is the point of recording
    both (ISSUE 6 acceptance)."""
    import mxnet_tpu as mx
    rep = mx.obs.report()
    best = None
    for e in rep["executors"]:
        if e.get("flops_per_sec") and \
                (best is None or e["flops_per_sec"] > best["flops_per_sec"]):
            best = e
    return {
        "obs_mfu": round(best["mfu"], 4)
        if best and best.get("mfu") is not None else None,
        "obs_flops_per_sec": best["flops_per_sec"] if best else None,
        "obs_compile_count": rep["counters"].get("obs_compile_count"),
        "obs_bind_ms_total": rep["counters"].get("obs_bind_ms_total"),
    }


def section_transformer():
    """Transformer-LM fused train step: tokens/s + MFU on one chip."""
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer

    if not mx.num_devices("tpu"):
        return {"skipped": "no tpu attached"}
    peak = _peak_flops(jax.devices()[0].device_kind)
    mx.amp.init("bfloat16")
    # ~0.67B-param GPT-2-medium-class decoder LM with the Pallas flash
    # attention kernel (fused fwd + dQ/dK/dV backward). Measured sweep on
    # this chip (see docs/perf.md): flash beats dense batch_dot attention
    # and L12/B8 is the MFU sweet spot; deeper/wider configs (1.5B) hit
    # the HBM ceiling with f32 master weights.
    L, D, H, T, V = 12, 2048, 16, 1024, 32000
    B = 8
    _note("bench: transformer bind start")
    t_bind = time.perf_counter()
    sym = transformer.get_symbol(vocab_size=V, num_layers=L, d_model=D,
                                 n_heads=H, seq_len=T, attention="flash")
    mod = mx.mod.Module(sym, context=mx.tpu(0))
    mod.bind(data_shapes=[("data", (B, T))],
             label_shapes=[("softmax_label", (B, T))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    bind_secs = round(time.perf_counter() - t_bind, 3)
    rng = np.random.RandomState(0)
    x = rng.randint(0, V, (B, T)).astype(np.float32)
    y = rng.randint(0, V, (B, T)).astype(np.float32)
    db = mx.io.DataBatch(data=[mx.nd.array(x, ctx=mx.tpu(0))],
                         label=[mx.nd.array(y, ctx=mx.tpu(0))])

    def drain():
        return float(np.asarray(
            mod._exec.arg_dict["lm_head_weight"].data[0, 0]))

    _note("bench: transformer bound; compiling")
    for _ in range(2):
        mod._fit_step(db)
    drain()
    mx.obs.report()     # open the obs rate window at the timed region
    _note("bench: transformer timing")
    iters = 12
    t0 = time.perf_counter()
    for _ in range(iters):
        mod._fit_step(db)
    drain()
    dt = time.perf_counter() - t0
    tok_s = B * T * iters / dt
    # PaLM-style accounting: 6*(non-embedding params) + 12*L*D*T per token
    n_params = transformer.param_count(V, L, D, H, seq_len=T)
    n_embed = V * D + T * D
    flops_per_tok = 6 * (n_params - n_embed) + 12 * L * D * T
    mfu = round(tok_s * flops_per_tok / peak, 4) if peak else None
    rec = {"transformer_tok_s": round(tok_s, 1), "transformer_mfu": mfu,
           "bind_secs": bind_secs}
    rec.update(_obs_crosscheck())
    return rec


def section_resnet():
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    on_tpu = bool(mx.num_devices("tpu"))
    ctx = mx.tpu(0) if on_tpu else mx.cpu(0)
    batch = BATCH if on_tpu else 8
    iters = ITERS if on_tpu else 3

    mx.amp.init("bfloat16")   # bf16 MXU compute, fp32 master weights
    _note("bench: resnet bind start")
    t_bind = time.perf_counter()

    # space-to-depth stem: mathematically identical to the 7x7/2 stem
    # on the same parameter, ~2 ms/step faster (docs/perf.md round-5
    # restructuring sweep)
    sym = resnet.get_symbol(num_classes=1000, num_layers=50, stem="s2d")
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[("data", (batch, 3, 224, 224))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9, "wd": 1e-4})
    bind_secs = round(time.perf_counter() - t_bind, 3)
    _note("bench: resnet bound in %.1fs" % bind_secs)

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (batch, 3, 224, 224)).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.float32)
    dbatch = mx.io.DataBatch(data=[mx.nd.array(x, ctx=ctx)],
                             label=[mx.nd.array(y, ctx=ctx)])

    def drain():
        # On the experimental remote-TPU plugin this machine uses,
        # block_until_ready returns before execution finishes — measured:
        # fencing with block_until_ready alone reported 147k img/s
        # (18x the chip's physical bf16 peak, impossible), while this
        # host read reports 2.2k img/s. Standard backends don't need
        # this; keep the host read as the fence wherever this bench runs.
        return float(np.asarray(
            mod._exec.arg_dict["fc1_weight"].data[0, 0]))

    _note("bench: resnet compiling")
    for _ in range(WARMUP):
        mod._fit_step(dbatch)
    drain()
    mx.obs.report()     # open the obs rate window at the timed region
    _note("bench: resnet timing")

    t0 = time.perf_counter()
    for _ in range(iters):
        mod._fit_step(dbatch)
    drain()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    peak = _peak_flops(jax.devices()[0].device_kind) if on_tpu else None
    mfu = round(img_s * TRAIN_FLOPS_PER_IMG / peak, 4) if peak else None
    rec = {
        "metric": "resnet50_train_bf16",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": mfu,
        "batch": batch,
        "flops_per_img": TRAIN_FLOPS_PER_IMG,
        "peak_flops": peak,
        "bind_secs": bind_secs,
    }
    rec.update(_obs_crosscheck())
    return rec


def run_section(name):
    fn = {"resnet": section_resnet, "transformer": section_transformer}[name]
    rec = dict(fn())
    rec["section"] = name
    print(json.dumps(rec), flush=True)


def _merge(records):
    """Assemble the flat single-record schema previous rounds consumed
    from whatever sections survived."""
    merged = {
        "metric": "resnet50_train_bf16", "value": None, "unit": "img/s",
        "vs_baseline": None, "mfu": None, "batch": None,
        "flops_per_img": TRAIN_FLOPS_PER_IMG, "peak_flops": None,
        "transformer_tok_s": None, "transformer_mfu": None,
        "bind_secs": {},
        "obs_mfu": {},
        "obs_bind_ms_total": {},
    }
    _per_section = ("bind_secs", "obs_mfu", "obs_bind_ms_total")
    errors = {}
    for name, rec in records.items():
        if "error" in rec:
            errors[name] = rec["error"]
            continue
        for k in merged:
            if k not in _per_section and k in rec:
                merged[k] = rec[k]
        for k in _per_section:
            # per-section records: the round-5 wedge was a 25-min bind,
            # invisible in a throughput-only record; obs_mfu is the
            # framework's own MFU next to this script's independent math
            if rec.get(k) is not None:
                merged[k][name] = rec[k]
    if errors:
        merged["errors"] = errors
    return merged


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--section":
        run_section(sys.argv[2])
        return
    timeout = float(os.environ.get("BENCH_SECTION_TIMEOUT_SECS", "600"))
    records = {}
    for name in SECTIONS:
        _note("bench: section %s (timeout %ds)" % (name, timeout))
        rec = {"section": name}
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--section", name],
                timeout=timeout, stdout=subprocess.PIPE, text=True)
            lines = [l for l in (proc.stdout or "").splitlines()
                     if l.strip()]
            if proc.returncode != 0:
                rec["error"] = "rc %d" % proc.returncode
            elif not lines:
                rec["error"] = "no output"
            else:
                rec = json.loads(lines[-1])
        except subprocess.TimeoutExpired:
            # the wedge case: this section hung; its sibling sections
            # still run and still report
            rec["error"] = "timeout after %ds" % timeout
        except Exception as exc:                           # noqa: BLE001
            rec["error"] = "%s: %s" % (type(exc).__name__, exc)
        records[name] = rec
        # incremental line-per-section: flushed NOW, so a later wedge
        # cannot take this section's result with it
        print(json.dumps(rec), flush=True)
    print(json.dumps(_merge(records)), flush=True)


if __name__ == "__main__":
    main()
